"""RetrievalMRR (reference ``retrieval/reciprocal_rank.py:20-69``)."""

from typing import Tuple

import jax

from metrics_tpu.functional.retrieval.engine import reciprocal_rank_per_group
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean Reciprocal Rank over queries."""

    def _group_scores(self, preds, target, group, n_groups) -> Tuple[Array, Array]:
        scores = reciprocal_rank_per_group(preds, target, group, n_groups)
        return scores, self._empty_mask(target, group, n_groups)

    def _metric(self, preds: Array, target: Array) -> Array:
        from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank

        return retrieval_reciprocal_rank(preds, target)
