"""RetrievalMRR (reference ``retrieval/reciprocal_rank.py:20-69``)."""

from typing import Tuple

import jax

from metrics_tpu.functional.retrieval.engine import reciprocal_rank_per_group
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean Reciprocal Rank over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMRR
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalMRR()
        >>> metric.update(preds, target, indexes=indexes)
        >>> float(metric.compute())
        1.0
    """

    def _group_scores(self, preds, target, group, n_groups) -> Tuple[Array, Array]:
        scores = reciprocal_rank_per_group(preds, target, group, n_groups)
        return scores, self._empty_mask(target, group, n_groups)

    def _metric(self, preds: Array, target: Array) -> Array:
        from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank

        return retrieval_reciprocal_rank(preds, target)
