"""RetrievalMAP (reference ``retrieval/average_precision.py:20-70``)."""

from typing import Tuple

import jax

from metrics_tpu.functional.retrieval.engine import average_precision_per_group
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean Average Precision over queries."""

    def _group_scores(self, preds, target, group, n_groups) -> Tuple[Array, Array]:
        scores = average_precision_per_group(preds, target, group, n_groups)
        return scores, self._empty_mask(target, group, n_groups)

    def _metric(self, preds: Array, target: Array) -> Array:
        from metrics_tpu.functional.retrieval.average_precision import retrieval_average_precision

        return retrieval_average_precision(preds, target)
