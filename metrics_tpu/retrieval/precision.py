"""RetrievalPrecision (reference ``retrieval/precision.py:22-99``)."""

from typing import Any, Optional, Tuple

import jax

from metrics_tpu.functional.retrieval.engine import precision_per_group
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalPrecision(RetrievalMetric):
    """Precision@k averaged over queries.

    Args:
        k: consider only the top k documents per query (None = all).
        adaptive_k: per query, use ``min(k, n_documents)`` as denominator.
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        adaptive_k: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if k is not None and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.k = k
        self.adaptive_k = adaptive_k

    def _group_scores(self, preds, target, group, n_groups) -> Tuple[Array, Array]:
        scores = precision_per_group(
            preds, target, group, n_groups, k=self.k, adaptive_k=self.adaptive_k
        )
        return scores, self._empty_mask(target, group, n_groups)

    def _metric(self, preds: Array, target: Array) -> Array:
        from metrics_tpu.functional.retrieval.precision import retrieval_precision

        return retrieval_precision(preds, target, k=self.k, adaptive_k=self.adaptive_k)
