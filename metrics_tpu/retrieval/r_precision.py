"""RetrievalRPrecision (reference ``retrieval/r_precision.py:20-70``)."""

from typing import Tuple

import jax

from metrics_tpu.functional.retrieval.engine import r_precision_per_group
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalRPrecision(RetrievalMetric):
    """R-Precision averaged over queries."""

    def _group_scores(self, preds, target, group, n_groups) -> Tuple[Array, Array]:
        scores = r_precision_per_group(preds, target, group, n_groups)
        return scores, self._empty_mask(target, group, n_groups)

    def _metric(self, preds: Array, target: Array) -> Array:
        from metrics_tpu.functional.retrieval.r_precision import retrieval_r_precision

        return retrieval_r_precision(preds, target)
