"""RetrievalFallOut (reference ``retrieval/fall_out.py:24-125``)."""

from typing import Any, Optional, Tuple

import jax

from metrics_tpu.functional.retrieval.engine import fall_out_per_group, group_relevant_counts
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalFallOut(RetrievalMetric):
    """Fall-out@k averaged over queries.

    Lower is better; a query is "empty" when it has no *negative* target
    (reference overrides ``compute`` for this — ``fall_out.py:93-122``).
    """

    higher_is_better = False
    _empty_kind = "negative"

    def __init__(
        self,
        empty_target_action: str = "pos",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if k is not None and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _empty_mask(self, target, group, n_groups) -> Array:
        # empty = no negative targets: count of (1 - target) per group == 0
        n_total = group_relevant_counts(jax.numpy.ones_like(target), group, n_groups)
        n_rel = group_relevant_counts(target, group, n_groups)
        return (n_total - n_rel) == 0

    def _group_scores(self, preds, target, group, n_groups) -> Tuple[Array, Array]:
        scores = fall_out_per_group(preds, target, group, n_groups, k=self.k)
        return scores, self._empty_mask(target, group, n_groups)

    def _metric(self, preds: Array, target: Array) -> Array:
        from metrics_tpu.functional.retrieval.fall_out import retrieval_fall_out

        return retrieval_fall_out(preds, target, k=self.k)
