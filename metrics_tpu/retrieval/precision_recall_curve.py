"""RetrievalPrecisionRecallCurve / RetrievalRecallAtFixedPrecision
(reference ``retrieval/precision_recall_curve.py:55-291``)."""

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.retrieval.engine import (
    contiguous_groups,
    group_relevant_counts,
    precision_recall_curve_per_group,
)
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


def _retrieval_recall_at_fixed_precision(
    precision: Array, recall: Array, top_k: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Max recall subject to precision >= min_precision
    (reference ``precision_recall_curve.py:25-52``)."""
    p = np.asarray(precision)
    r = np.asarray(recall)
    k = np.asarray(top_k)
    candidates = [(rv, kv) for pv, rv, kv in zip(p, r, k) if pv >= min_precision]
    if candidates:
        max_recall, best_k = max(candidates)
    else:
        max_recall, best_k = 0.0, len(k)
    if max_recall == 0.0:
        best_k = len(k)
    return jnp.asarray(max_recall, dtype=jnp.float32), jnp.asarray(best_k, dtype=jnp.int32)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Mean precision/recall at every k in ``1..max_k`` over queries.

    Vectorized delta: all queries are scored in one scatter+cumsum program
    (``engine.precision_recall_curve_per_group``) instead of the reference's
    per-query loop (``precision_recall_curve.py:184-201``).
    """

    def __init__(
        self,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs
        )
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        self.max_k = max_k
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.adaptive_k = adaptive_k

    def compute(self) -> Tuple[Array, Array, Array]:
        indexes = self.buffer_values("indexes")
        preds = self.buffer_values("preds")
        target = self.buffer_values("target")
        group, n_groups = contiguous_groups(indexes)

        max_k = self.max_k
        if max_k is None:
            counts = np.bincount(np.asarray(group), minlength=n_groups)
            max_k = int(counts.max()) if counts.size else 1

        precision, recall = precision_recall_curve_per_group(
            preds, target, group, n_groups, max_k=max_k, adaptive_k=self.adaptive_k
        )
        empty = group_relevant_counts(target, group, n_groups) == 0
        top_k = jnp.arange(1, max_k + 1)
        if self.empty_target_action == "error" and bool(jnp.any(empty)):
            raise ValueError("`compute` method was provided with a query with no positive target.")
        if self.empty_target_action == "pos":
            precision = jnp.where(empty[:, None], 1.0, precision)
            recall = jnp.where(empty[:, None], 1.0, recall)
        elif self.empty_target_action == "neg":
            precision = jnp.where(empty[:, None], 0.0, precision)
            recall = jnp.where(empty[:, None], 0.0, recall)
        elif self.empty_target_action == "skip":
            keep = ~empty
            n_keep = keep.sum()
            w = keep.astype(precision.dtype)[:, None]
            precision = jnp.where(
                n_keep > 0, (precision * w).sum(0) / jnp.clip(n_keep, 1, None), jnp.zeros((max_k,))
            )
            recall = jnp.where(
                n_keep > 0, (recall * w).sum(0) / jnp.clip(n_keep, 1, None), jnp.zeros((max_k,))
            )
            return precision, recall, top_k
        return precision.mean(axis=0), recall.mean(axis=0), top_k


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Max recall with precision >= ``min_precision``
    (reference ``precision_recall_curve.py:212-291``)."""

    def __init__(
        self,
        min_precision: float = 0.0,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            max_k=max_k,
            adaptive_k=adaptive_k,
            empty_target_action=empty_target_action,
            ignore_index=ignore_index,
            **kwargs,
        )
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a positive float between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        precisions, recalls, top_k = super().compute()
        return _retrieval_recall_at_fixed_precision(precisions, recalls, top_k, self.min_precision)
