"""RetrievalHitRate (reference ``retrieval/hit_rate.py:22-92``)."""

from typing import Any, Optional, Tuple

import jax

from metrics_tpu.functional.retrieval.engine import hit_rate_per_group
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalHitRate(RetrievalMetric):
    """HitRate@k averaged over queries."""

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if k is not None and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _group_scores(self, preds, target, group, n_groups) -> Tuple[Array, Array]:
        scores = hit_rate_per_group(preds, target, group, n_groups, k=self.k)
        return scores, self._empty_mask(target, group, n_groups)

    def _metric(self, preds: Array, target: Array) -> Array:
        from metrics_tpu.functional.retrieval.hit_rate import retrieval_hit_rate

        return retrieval_hit_rate(preds, target, k=self.k)
