"""Exporters: structured report, JSON dump, and Prometheus text format.

The Prometheus renderer follows the text exposition format (one
``name{labels} value`` line per series, ``# TYPE`` headers, counter series
suffixed ``_total``). ``parse_prometheus_text`` is the matching line parser
used by tests to round-trip the output.
"""

import json
import math
import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

from metrics_tpu.obs.core import (
    CounterKey,
    _rt,
    counters_snapshot,
    spans_snapshot,
    sync_reports,
)

_PROM_PREFIX = "metrics_tpu_"
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def report() -> Dict[str, Any]:
    """Everything the runtime knows, as plain JSON-serializable data."""
    counters = [
        {"name": name, "labels": dict(labels), "value": value}
        for (name, labels), value in sorted(counters_snapshot().items())
    ]
    spans = [
        {
            "name": name,
            "labels": dict(labels),
            "count": int(agg[0]),
            "total_secs": round(agg[1], 6),
            "max_secs": round(agg[2], 6),
        }
        for (name, labels), agg in sorted(spans_snapshot().items())
    ]
    with _rt.lock:
        events = list(_rt.events)
    return {
        "enabled": _rt.enabled,
        "counters": counters,
        "spans": spans,
        "sync_reports": sync_reports(),
        "recent_events": events,
    }


def dump_json(path: str, indent: int = 2) -> str:
    """Write ``report()`` to ``path``; returns the path for chaining."""
    with open(path, "w") as fh:
        json.dump(report(), fh, indent=indent, sort_keys=True, default=str)
        fh.write("\n")
    return path


# ---------------------------------------------------------------------------
# Prometheus text format


def _prom_name(name: str) -> str:
    return _PROM_PREFIX + _NAME_RE.sub("_", name)


def _prom_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    parts = []
    for key, value in labels:
        escaped = value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        parts.append(f'{_NAME_RE.sub("_", key)}="{escaped}"')
    return "{" + ",".join(parts) + "}"


def _fmt(value: float) -> str:
    if isinstance(value, float) and not value.is_integer():
        return repr(round(value, 9))
    return str(int(value))


def prometheus_text() -> str:
    """Render counters and span aggregates in Prometheus exposition format."""
    lines: List[str] = []

    by_name: Dict[str, List[Tuple[CounterKey, float]]] = {}
    for key, value in sorted(counters_snapshot().items()):
        by_name.setdefault(key[0], []).append((key, value))
    for name, series in by_name.items():
        prom = _prom_name(name) + "_total"
        lines.append(f"# TYPE {prom} counter")
        for (_, labels), value in series:
            lines.append(f"{prom}{_prom_labels(labels)} {_fmt(value)}")

    spans = sorted(spans_snapshot().items())
    if spans:
        for suffix, idx, kind in (
            ("span_count_total", 0, "counter"),
            ("span_seconds_total", 1, "counter"),
            ("span_seconds_max", 2, "gauge"),
        ):
            prom = _PROM_PREFIX + suffix
            lines.append(f"# TYPE {prom} {kind}")
            for (name, labels), agg in spans:
                full = (("span", name),) + labels
                lines.append(f"{prom}{_prom_labels(full)} {_fmt(agg[idx])}")

    return "\n".join(lines) + "\n" if lines else ""


_METRIC_VALUE_GAUGE = _PROM_PREFIX + "metric_value"


def _gauge_fmt(value: float) -> str:
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return _fmt(value)


def metric_values_prometheus_text(values: Any) -> str:
    """Render *computed metric values* as labeled gauges.

    The counters/spans in :func:`prometheus_text` describe the runtime; this
    exporter describes the evaluation results themselves, as one gauge family
    ``metrics_tpu_metric_value{job="..."}`` — the scrape surface the serve
    layer's ``/metrics`` endpoint adds on top of the counters.

    ``values`` is either a mapping ``job -> value`` or any object with an
    ``export_values()`` method returning one (duck-typed so
    ``metrics_tpu.serve.MetricRegistry`` plugs in without obs importing it).
    Each value may be:

    * a scalar (anything ``float()`` accepts) — one series per job;
    * a mapping ``component -> scalar`` — one series per component, labeled
      ``component="..."`` (dict-computing metrics, named vector components);
    * an iterable of ``(labels_dict, scalar)`` pairs — arbitrary extra labels
      (the registry uses this for per-stream ``top_k`` exports).

    NaN-safe: non-finite values render as Prometheus' literal ``NaN`` /
    ``+Inf`` / ``-Inf`` instead of crashing the scrape, and
    :func:`parse_prometheus_text` round-trips them.
    """
    if not isinstance(values, Mapping) and hasattr(values, "export_values"):
        values = values.export_values()
    series: List[Tuple[Tuple[Tuple[str, str], ...], float]] = []
    for job in sorted(values):
        value = values[job]
        base = (("job", str(job)),)
        if isinstance(value, Mapping):
            for comp in sorted(value):
                series.append((base + (("component", str(comp)),), float(value[comp])))
        elif isinstance(value, (list, tuple)):
            for labels, v in value:
                extra = tuple(sorted((str(k), str(lv)) for k, lv in dict(labels).items()))
                series.append((base + extra, float(v)))
        else:
            series.append((base, float(value)))
    if not series:
        return ""
    lines = [f"# TYPE {_METRIC_VALUE_GAUGE} gauge"]
    for labels, v in series:
        lines.append(f"{_METRIC_VALUE_GAUGE}{_prom_labels(labels)} {_gauge_fmt(v)}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Parse exposition-format lines back into {(name, labels): value}.

    Understands the subset ``prometheus_text`` emits (no timestamps, no
    exemplars) plus escaped label values; raises ValueError on malformed
    lines so tests catch renderer drift.
    """
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_src, _, value_src = rest.rpartition("} ")
            if not _:
                raise ValueError(f"malformed series line: {raw!r}")
            labels = _parse_labels(labels_src)
        else:
            name, _, value_src = line.rpartition(" ")
            labels = ()
        if not name or not value_src:
            raise ValueError(f"malformed series line: {raw!r}")
        out[(name, labels)] = float(value_src)
    return out


def _parse_labels(src: str) -> Tuple[Tuple[str, str], ...]:
    labels: List[Tuple[str, str]] = []
    i, n = 0, len(src)
    while i < n:
        eq = src.index("=", i)
        key = src[i:eq]
        if src[eq + 1] != '"':
            raise ValueError(f"unquoted label value near {src[i:]!r}")
        j = eq + 2
        buf: List[str] = []
        while j < n:
            ch = src[j]
            if ch == "\\":
                nxt = src[j + 1]
                buf.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
                j += 2
                continue
            if ch == '"':
                break
            buf.append(ch)
            j += 1
        else:
            raise ValueError(f"unterminated label value near {src[i:]!r}")
        labels.append((key, "".join(buf)))
        i = j + 1
        if i < n and src[i] == ",":
            i += 1
    return tuple(labels)


# ---------------------------------------------------------------------------
# compact summaries (bench.py attribution sections)


def summarize_counters(
    counters: Optional[Dict[CounterKey, float]] = None,
) -> Dict[str, Any]:
    """Fold raw counters into the compact attribution dict bench.py embeds.

    Accepts a snapshot (or a delta of two snapshots) from
    ``counters_snapshot``; zero-valued sections are omitted so quiet configs
    stay quiet in the output.
    """
    if counters is None:
        counters = counters_snapshot()
    recompiles = 0.0
    by_metric: Dict[str, float] = {}
    sync: Dict[str, float] = {}
    streaming: Dict[str, float] = {}
    multistream: Dict[str, float] = {}
    ckpt: Dict[str, float] = {}
    serve: Dict[str, float] = {}
    iou_hits = iou_misses = 0.0
    fallbacks = 0.0
    faults = 0.0
    suppressed = 0.0
    for (name, labels), value in counters.items():
        if not value:
            continue
        if name == "jit_traces":
            recompiles += value
            metric = dict(labels).get("metric", "?")
            by_metric[metric] = by_metric.get(metric, 0) + value
        elif name.startswith("sync."):
            field = name[len("sync."):]
            sync[field] = sync.get(field, 0) + value
        elif name.startswith("streaming."):
            field = name[len("streaming."):]
            streaming[field] = streaming.get(field, 0) + value
        elif name.startswith("multistream."):
            field = name[len("multistream."):]
            multistream[field] = multistream.get(field, 0) + value
        elif name.startswith("ckpt."):
            field = name[len("ckpt."):]
            ckpt[field] = ckpt.get(field, 0) + value
        elif name.startswith("serve."):
            field = name[len("serve."):]
            serve[field] = serve.get(field, 0) + value
        elif name == "iou_cache.hits":
            iou_hits += value
        elif name == "iou_cache.misses":
            iou_misses += value
        elif name == "eager_fallback":
            fallbacks += value
        elif name == "chaos.faults":
            faults += value
        elif name == "warn_once.suppressed":
            suppressed += value
    out: Dict[str, Any] = {}
    if recompiles:
        out["recompiles"] = int(recompiles)
        out["recompiles_by_metric"] = {k: int(v) for k, v in sorted(by_metric.items())}
    if sync:
        out["sync"] = {
            k: (round(v, 6) if k in ("backoff_secs", "overlap_secs") else int(v))
            for k, v in sorted(sync.items())
        }
    if streaming:
        out["streaming"] = {k: int(v) for k, v in sorted(streaming.items())}
    if multistream:
        out["multistream"] = {k: int(v) for k, v in sorted(multistream.items())}
    if ckpt:
        out["ckpt"] = {k: int(v) for k, v in sorted(ckpt.items())}
    if serve:
        # forwarder backoff is wall-clock seconds, the one float in the
        # serve bucket (same treatment as sync's backoff_secs above)
        out["serve"] = {
            k: (round(v, 6) if k == "forwarder_backoff_secs" else int(v))
            for k, v in sorted(serve.items())
        }
    if iou_hits or iou_misses:
        out["iou_cache"] = {
            "hits": int(iou_hits),
            "misses": int(iou_misses),
            "hit_rate": round(iou_hits / (iou_hits + iou_misses), 4),
        }
    if fallbacks:
        out["eager_fallbacks"] = int(fallbacks)
    if faults:
        out["chaos_faults"] = int(faults)
    if suppressed:
        out["warnings_suppressed"] = int(suppressed)
    return out
