"""Runtime observability for metrics_tpu: spans, counters, exporters.

Quick start::

    import metrics_tpu.obs as obs

    obs.enable()                  # or METRICS_TPU_OBS=1 in the environment
    ... run your eval loop ...
    print(obs.report())           # spans, counters, recent sync reports
    obs.dump_json("obs.json")
    print(obs.prometheus_text())  # scrape-ready exposition format

Counters (recompiles, sync bytes/attempts, cache hits, fault injections)
are always on — they only tick on cold paths. Spans are sampled only while
enabled; disabled, ``obs.span`` returns a shared no-op and the hot update
path pays a single flag check. See ``docs/observability.md``.
"""

from metrics_tpu.obs.core import (
    NOOP_SPAN,
    count_trace,
    counter_inc,
    counter_value,
    counters_snapshot,
    disable,
    enable,
    enabled,
    record_sync_report,
    reset,
    span,
    spans_snapshot,
    sync_reports,
)
from metrics_tpu.obs.exporters import (
    dump_json,
    metric_values_prometheus_text,
    parse_prometheus_text,
    prometheus_text,
    report,
    summarize_counters,
)
from metrics_tpu.obs.logging import warn_once

__all__ = [
    "NOOP_SPAN",
    "count_trace",
    "counter_inc",
    "counter_value",
    "counters_snapshot",
    "disable",
    "dump_json",
    "enable",
    "enabled",
    "metric_values_prometheus_text",
    "parse_prometheus_text",
    "prometheus_text",
    "record_sync_report",
    "report",
    "reset",
    "span",
    "spans_snapshot",
    "summarize_counters",
    "sync_reports",
    "warn_once",
]
