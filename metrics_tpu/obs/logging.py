"""Rank-aware warn-once helper.

Eager-path warnings inside metric compute/update bodies fire on every call
— and, without rank gating, on every host. ``warn_once`` emits a warning at
most once per process (rank 0 only) and counts suppressions so the obs
report still shows how often the condition recurred.
"""

import threading
import warnings
from typing import Any, Optional, Set, Tuple, Type

from metrics_tpu.obs import core as _core
from metrics_tpu.utils.prints import _process_index

_warned: Set[Tuple[str, ...]] = set()
_lock = threading.Lock()


def _clear() -> None:
    with _lock:
        _warned.clear()


_core._reset_hooks.append(_clear)


def warn_once(
    message: str,
    category: Type[Warning] = UserWarning,
    key: Optional[str] = None,
    stacklevel: int = 3,
    **kwargs: Any,
) -> bool:
    """Warn on rank 0, once per process per ``key`` (default: the message).

    Returns True if the warning was newly registered this call. Repeats are
    counted under the ``warn_once.suppressed`` counter instead of re-warning,
    so per-batch degenerate-input warnings cost one line per run, not one per
    rank per step. ``obs.reset()`` clears the registry.
    """
    dedup: Tuple[str, ...] = (category.__name__, key if key is not None else message)
    with _lock:
        if dedup in _warned:
            first = False
        else:
            _warned.add(dedup)
            first = True
    site = key if key is not None else category.__name__
    if not first:
        _core.counter_inc("warn_once.suppressed", site=site)
        return False
    _core.counter_inc("warn_once.emitted", site=site)
    if _process_index() == 0:
        warnings.warn(message, category, stacklevel=stacklevel, **kwargs)
    return True
