"""Runtime telemetry core: spans, counters, and the sync-report registry.

Two cost tiers, chosen per instrument:

* **Counters are always on.** Every counter site lives on a cold path —
  trace time (a Python body runs once per compilation, not per dispatch),
  cross-host sync, cache assembly, fault injection, eager demotion — so the
  bookkeeping is free relative to the work it annotates. This is what lets
  ``bench.py`` attach recompile/sync attribution to every run without the
  caller ever calling :func:`enable`.
* **Spans are gated.** ``span()`` checks a module-level flag and returns a
  shared no-op singleton when disabled; hot callers (``Metric.update``)
  additionally guard on ``_rt.enabled`` so the disabled path costs one
  attribute load and a branch.

Everything here is process-local and thread-safe; nothing imports jax, so
the module is safe to pull in from any layer of the package.
"""

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

_EVENT_RING = 256
_SYNC_RING = 64

LabelsKey = Tuple[Tuple[str, str], ...]
CounterKey = Tuple[str, LabelsKey]


class _Runtime:
    """Singleton holding all telemetry state behind one lock."""

    __slots__ = ("enabled", "lock", "counters", "spans", "events", "sync_reports", "tls")

    def __init__(self) -> None:
        self.enabled = False
        self.lock = threading.Lock()
        # (name, labels) -> float
        self.counters: Dict[CounterKey, float] = {}
        # (name, labels) -> [count, total_secs, max_secs]
        self.spans: Dict[CounterKey, List[float]] = {}
        self.events: deque = deque(maxlen=_EVENT_RING)
        self.sync_reports: deque = deque(maxlen=_SYNC_RING)
        self.tls = threading.local()


_rt = _Runtime()

# Callables run by reset() so satellite stores (e.g. the warn-once registry)
# clear together with the core state without core importing them.
_reset_hooks: List[Callable[[], None]] = []


def _labels_key(labels: Dict[str, Any]) -> LabelsKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


# ---------------------------------------------------------------------------
# enable / disable


def enable() -> None:
    """Turn span tracing on (counters are always on)."""
    _rt.enabled = True


def disable() -> None:
    _rt.enabled = False


def enabled() -> bool:
    return _rt.enabled


def reset() -> None:
    """Clear all counters, spans, events, sync reports, and hook stores."""
    with _rt.lock:
        _rt.counters.clear()
        _rt.spans.clear()
        _rt.events.clear()
        _rt.sync_reports.clear()
    for hook in _reset_hooks:
        hook()


# ---------------------------------------------------------------------------
# counters (always on)


def counter_inc(name: str, value: float = 1, **labels: Any) -> None:
    key = (name, _labels_key(labels))
    with _rt.lock:
        _rt.counters[key] = _rt.counters.get(key, 0) + value


def counter_value(name: str, **labels: Any) -> float:
    key = (name, _labels_key(labels))
    with _rt.lock:
        return _rt.counters.get(key, 0)


def counters_snapshot() -> Dict[CounterKey, float]:
    """Point-in-time copy of every counter; keys are (name, labels) pairs."""
    with _rt.lock:
        return dict(_rt.counters)


def count_trace(metric: str, fn: str) -> None:
    """Called *inside* jitted function bodies: runs once per (re)trace.

    Python side effects in a traced body execute at trace time only, so this
    counts compilations with zero dispatch-time cost. A rising count for a
    fixed metric means shape/dtype churn is defeating the jit cache.
    """
    counter_inc("jit_traces", metric=metric, fn=fn)


# ---------------------------------------------------------------------------
# spans


class _NoopSpan:
    """Shared do-nothing span returned whenever tracing is disabled."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **labels: Any) -> "_NoopSpan":
        return self


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("name", "labels", "_start", "_parent")

    def __init__(self, name: str, labels: Dict[str, Any]) -> None:
        self.name = name
        self.labels = labels

    def set(self, **labels: Any) -> "_Span":
        self.labels.update(labels)
        return self

    def __enter__(self) -> "_Span":
        stack = getattr(_rt.tls, "stack", None)
        if stack is None:
            stack = _rt.tls.stack = []
        self._parent = stack[-1].name if stack else None
        stack.append(self)
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> bool:
        dur = time.perf_counter() - self._start
        stack = getattr(_rt.tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        labels = self.labels
        if self._parent is not None:
            labels = dict(labels)
            labels["parent"] = self._parent
        key = (self.name, _labels_key(labels))
        with _rt.lock:
            agg = _rt.spans.get(key)
            if agg is None:
                agg = _rt.spans[key] = [0, 0.0, 0.0]
            agg[0] += 1
            agg[1] += dur
            if dur > agg[2]:
                agg[2] = dur
            _rt.events.append({"span": self.name, "labels": dict(labels), "secs": dur})
        return False


def span(name: str, **labels: Any) -> Any:
    """Context manager timing a region; returns a shared no-op when disabled.

    Nesting is tracked per thread: a span entered inside another records a
    ``parent`` label with the enclosing span's name, which is how
    ``MetricCollection`` attributes member time to the collection call.
    """
    if not _rt.enabled:
        return NOOP_SPAN
    return _Span(name, labels)


def spans_snapshot() -> Dict[CounterKey, List[float]]:
    """Copy of span aggregates: (name, labels) -> [count, total_secs, max_secs]."""
    with _rt.lock:
        return {k: list(v) for k, v in _rt.spans.items()}


# ---------------------------------------------------------------------------
# sync-report registry (absorbs Metric.last_sync_report; always on)

_SYNC_COUNTER_KEYS = (
    "bytes_gathered",
    "gather_calls",
    "retries",
    "attempts",
    "bytes_saved",
    # preflight metadata traffic is accounted apart from state payload so
    # `bytes_gathered` means the same thing on every eager backend
    "preflight_bytes",
    "preflight_calls",
    # the mesh backend's in-program path has no wire bytes to count
    "in_xla_reductions",
)


def record_sync_report(metric: str, report: Dict[str, Any]) -> None:
    """File one per-sync telemetry dict into the queryable registry.

    Fed by ``Metric._finish_sync_report`` after every distributed sync
    attempt (success or failure); also rolls the headline figures into the
    ``sync.*`` counters so exporters see process totals without walking the
    ring.
    """
    entry = {"metric": metric}
    entry.update(report)
    with _rt.lock:
        _rt.sync_reports.append(entry)
    counter_inc("sync.reports", metric=metric)
    if report.get("error"):
        counter_inc("sync.errors", metric=metric)
    if "delta" in report:
        # the delta/full split only exists for gathers that actually ran; the
        # single source is the report so per-metric and process totals agree
        counter_inc("sync.delta_syncs" if report["delta"] else "sync.full_syncs", metric=metric)
    for key in _SYNC_COUNTER_KEYS:
        val = report.get(key) or 0
        if val:
            counter_inc("sync." + key, int(val), metric=metric)
    backoff = report.get("backoff_secs") or 0.0
    if backoff:
        counter_inc("sync.backoff_secs", float(backoff), metric=metric)
    overlap = report.get("overlap_secs") or 0.0
    if overlap:
        counter_inc("sync.overlap_secs", float(overlap), metric=metric)


def sync_reports(metric: Optional[str] = None) -> List[Dict[str, Any]]:
    """Recent sync reports (newest last), optionally filtered by metric name."""
    with _rt.lock:
        out = list(_rt.sync_reports)
    if metric is not None:
        out = [r for r in out if r.get("metric") == metric]
    return out


# ---------------------------------------------------------------------------

if os.environ.get("METRICS_TPU_OBS", "").strip().lower() in ("1", "true", "yes", "on"):
    enable()
