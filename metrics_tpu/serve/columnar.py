"""Pre-allocated columnar staging between frontend threads and forwarders.

The coordinator's ingest path never builds a Python object per record:
request threads copy a batch's scalar columns straight into a
:class:`ColumnRing` — one fixed set of numpy arrays allocated up front —
and the shard's forwarder thread drains **views** of the same storage and
ships them (serialized over HTTP, or copied once at the in-process enqueue
boundary).  One copy in, views out; allocation count is O(1) per ring, not
O(records).

Correctness of the zero-copy hand-off is the two-phase drain: ``drain``
hands out views and marks the rows *pending*; the slots only become
writable again after the forwarder calls ``commit``, so a producer can
never overwrite rows an in-flight forward still references.  Writers and
the single drainer share one short mutex; the drainer's idle wait is a
**timed** condition wait (the lock-order pass checks this module with no
opt-outs).

Backpressure mirrors :class:`~metrics_tpu.serve.ingest.IngestQueue`: a
batch that does not fit is rejected whole (counted in
``serve.records_rejected``) rather than stalling the HTTP thread or
growing without bound.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.obs import core as _obs
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["ColumnRing"]


class ColumnRing:
    """Fixed-capacity columnar ring buffer: many writers, one drainer.

    Args:
        arity: number of value columns (the job metric's positional args).
        capacity: ring rows; also the burst the frontend can absorb while
            the forwarder is busy.
        with_ids: allocate the int32 ``stream_ids`` lane (multistream jobs).
        dtype: dtype of the value columns (scalar rows only — jobs with
            per-row array values take the :class:`Record` path instead).
    """

    def __init__(
        self,
        arity: int,
        capacity: int = 8192,
        with_ids: bool = False,
        dtype: np.dtype = np.float32,
    ) -> None:
        if int(arity) < 1:
            raise MetricsTPUUserError(f"arity must be >= 1, got {arity}")
        if int(capacity) < 1:
            raise MetricsTPUUserError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._cols: List[np.ndarray] = [
            np.empty((self.capacity,), dtype) for _ in range(int(arity))
        ]
        self._ids: Optional[np.ndarray] = (
            np.empty((self.capacity,), np.int32) if with_ids else None
        )
        self._lock = threading.Lock()
        try:  # named in the runtime lock-witness graph
            self._lock.witness_name = "ColumnRing._lock"
        except AttributeError:
            pass
        self._readable = threading.Condition(self._lock)
        self._tail = 0  # first committed-unread slot
        self._count = 0  # buffered rows, pending included
        self._pending = 0  # rows handed to the drainer, not yet committed
        self._hwm = 0  # deepest the ring has ever been (autoscaler signal)
        # WAL frame spans: [seq_or_None, rows] per buffered put batch, in
        # ring order, summing to _count.  Allocated lazily on the first
        # framed put so non-WAL rings pay nothing.
        self._spans: Optional[Deque[List[object]]] = None

    @property
    def arity(self) -> int:
        return len(self._cols)

    def depth(self) -> int:
        with self._lock:
            return self._count

    def pending(self) -> int:
        """Rows handed to the drainer and not yet committed (in flight)."""
        with self._lock:
            return self._pending

    def high_water(self) -> int:
        """Deepest occupancy this ring has ever reached."""
        with self._lock:
            return self._hwm

    # ----------------------------------------------------------------- write
    def put(
        self,
        cols: Sequence[np.ndarray],
        stream_ids: Optional[np.ndarray] = None,
        frame: Optional[
            Callable[[List[np.ndarray], Optional[np.ndarray]], int]
        ] = None,
    ) -> bool:
        """Copy one batch into the ring; ``False`` (counted) when it does
        not fit — backpressure, not blocking.

        ``frame``, when given, is called *under the ring mutex, only after
        the batch is guaranteed accepted*, with the dtype-converted columns
        and ids; it must return the batch's WAL sequence number.  Running
        the WAL append inside the critical section pins ring order == seq
        order (the exactly-once replay invariant) without a second lock,
        and a rejected batch never consumes a seq or touches the log.
        """
        if len(cols) != len(self._cols):
            raise MetricsTPUUserError(
                f"ring holds {len(self._cols)} column(s), got {len(cols)}"
            )
        first = np.asarray(cols[0]).reshape(-1)
        n = int(first.shape[0])
        if (stream_ids is None) != (self._ids is None):
            raise MetricsTPUUserError(
                "stream_ids must be "
                + ("present" if self._ids is not None else "None")
                + " for this ring"
            )
        if n == 0:
            return True
        # convert + validate every lane BEFORE taking the mutex, so a
        # ragged batch cannot leave half-written slots or hold the lock
        # through a raise
        arrs = [np.asarray(c, rc.dtype).reshape(-1) for rc, c in zip(self._cols, cols)]
        if any(a.shape[0] != n for a in arrs):
            raise MetricsTPUUserError(
                f"ragged batch: columns disagree on the row count ({n})"
            )
        ids = None
        if self._ids is not None:
            ids = np.asarray(stream_ids, np.int32).reshape(-1)
            if ids.shape[0] != n:
                raise MetricsTPUUserError(
                    f"ragged batch: {ids.shape[0]} stream_ids for {n} rows"
                )
        if n > self.capacity:
            _obs.counter_inc("serve.records_rejected", n, reason="ring_burst")
            return False
        with self._readable:
            if self.capacity - self._count < n:
                _obs.counter_inc("serve.records_rejected", n, reason="ring_full")
                return False
            head = (self._tail + self._count) % self.capacity
            split = min(n, self.capacity - head)
            for ring_col, arr in zip(self._cols, arrs):
                ring_col[head : head + split] = arr[:split]
                if split < n:
                    ring_col[: n - split] = arr[split:]
            if ids is not None:
                self._ids[head : head + split] = ids[:split]
                if split < n:
                    self._ids[: n - split] = ids[split:]
            if frame is not None:
                if self._spans is None:
                    self._spans = deque()
                    if self._count:
                        # rows staged before WAL mode engaged: unframed
                        self._spans.append([None, self._count])
                seq = int(frame(arrs, ids))
                self._spans.append([seq, n])
            elif self._spans is not None:
                self._spans.append([None, n])
            self._count += n
            if self._count > self._hwm:
                # counter carries the delta so the summed counter IS the
                # fleet-wide high-water mark (autoscaler pressure signal)
                _obs.counter_inc(
                    "serve.ring_occupancy_hwm", self._count - self._hwm
                )
                self._hwm = self._count
            self._readable.notify()
        return True

    # ----------------------------------------------------------------- drain
    def drain(
        self, timeout: float, max_rows: Optional[int] = None
    ) -> Optional[Tuple[List[np.ndarray], Optional[np.ndarray], int]]:
        """Borrow the next contiguous run of rows as views (single drainer).

        Returns ``(col_views, id_view_or_None, n)`` or ``None`` when
        nothing arrives within ``timeout``.  The rows stay reserved until
        :meth:`commit`; exactly one drain may be outstanding at a time.
        Wraparound shows up as two successive drains — views must be
        contiguous to be zero-copy.
        """
        if self._pending:
            raise MetricsTPUUserError(
                "previous drain not committed; call commit(n) first"
            )
        with self._readable:
            if self._count == 0:
                self._readable.wait(timeout)
            avail = self._count
            if avail == 0:
                return None
            run = min(avail, self.capacity - self._tail)
            if max_rows is not None:
                run = min(run, int(max_rows))
            views = [c[self._tail : self._tail + run] for c in self._cols]
            id_view = (
                None
                if self._ids is None
                else self._ids[self._tail : self._tail + run]
            )
            self._pending = run
            return views, id_view, run

    def drain_frames(
        self, timeout: float, max_rows: Optional[int] = None
    ) -> Optional[
        Tuple[
            List[np.ndarray],
            Optional[np.ndarray],
            int,
            List[Tuple[Optional[int], int]],
        ]
    ]:
        """:meth:`drain`, but clipped to whole WAL frames.

        Returns ``(col_views, id_view_or_None, n, spans)`` where ``spans``
        is ``[(seq_or_None, rows), ...]`` partitioning the ``n`` rows into
        the put batches they arrived as — the forwarder ships them so the
        worker can seq-dedup per frame.  On a ring with no framed puts this
        degrades to plain :meth:`drain` with one anonymous span.

        The run never ends mid-frame: shipping half a frame under a seq
        would let a retry double-apply the other half.  When the frame at
        the front straddles the ring's wrap point (so no contiguous view
        can cover it), that one frame is returned as a **copy** — the only
        allocation on this path, bounded to one frame per ring cycle.
        """
        if self._pending:
            raise MetricsTPUUserError(
                "previous drain not committed; call commit(n) first"
            )
        with self._readable:
            if self._count == 0:
                self._readable.wait(timeout)
            avail = self._count
            if avail == 0:
                return None
            run = min(avail, self.capacity - self._tail)
            if max_rows is not None:
                run = min(run, int(max_rows))
            if self._spans is None:
                views = [c[self._tail : self._tail + run] for c in self._cols]
                id_view = (
                    None
                    if self._ids is None
                    else self._ids[self._tail : self._tail + run]
                )
                self._pending = run
                return views, id_view, run, [(None, run)]
            covered, spans = self._span_prefix(run)
            if covered:
                views = [c[self._tail : self._tail + covered] for c in self._cols]
                id_view = (
                    None
                    if self._ids is None
                    else self._ids[self._tail : self._tail + covered]
                )
                self._pending = covered
                return views, id_view, covered, spans
            # the front frame is wider than the contiguous window (wrap) or
            # the max_rows clip: hand out exactly that frame, copying the
            # two arcs together when it wraps
            seq, rows = self._spans[0]
            rows = int(rows)
            views = [self._arc(c, rows) for c in self._cols]
            id_view = None if self._ids is None else self._arc(self._ids, rows)
            self._pending = rows
            return views, id_view, rows, [(seq, rows)]  # type: ignore[list-item]

    def _arc(self, col: np.ndarray, rows: int) -> np.ndarray:
        first = min(rows, self.capacity - self._tail)
        if first >= rows:
            return col[self._tail : self._tail + rows]
        return np.concatenate([col[self._tail :], col[: rows - first]])

    def _span_prefix(
        self, limit: int
    ) -> Tuple[int, List[Tuple[Optional[int], int]]]:
        covered = 0
        out: List[Tuple[Optional[int], int]] = []
        for seq, rows in self._spans:  # type: ignore[union-attr]
            if covered + int(rows) > limit:  # type: ignore[arg-type]
                break
            out.append((seq, int(rows)))  # type: ignore[arg-type]
            covered += int(rows)  # type: ignore[arg-type]
        return covered, out

    def commit(self, n: int) -> None:
        """Release the first ``n`` rows of the outstanding drain: their
        slots become writable and the views returned for them go stale."""
        n = int(n)
        with self._lock:
            if n < 0 or n > self._pending:
                raise MetricsTPUUserError(
                    f"commit({n}) does not match the outstanding drain "
                    f"({self._pending} row(s))"
                )
            if n < self._pending:
                # the park lane: drained rows NOT released stay buffered and
                # will be re-drained later (forward failure, held job,
                # split-owner prefix) — commit(0) parks the whole drain
                _obs.counter_inc("serve.parked_rows", self._pending - n)
            if self._spans is not None and n:
                remaining = n
                while remaining and self._spans:
                    span = self._spans[0]
                    rows = int(span[1])  # type: ignore[arg-type]
                    if rows <= remaining:
                        remaining -= rows
                        self._spans.popleft()
                        continue
                    # a commit inside a frame (split-owner prefix, mid-
                    # resize): the surviving remainder can no longer replay
                    # exactly-once as a frame, so it is demoted to unframed
                    # rows — the documented resize/WAL overlap caveat
                    span[1] = rows - remaining
                    if span[0] is not None:
                        span[0] = None
                        _obs.counter_inc("serve.wal_unframed_rows", rows - remaining)
                    remaining = 0
            self._tail = (self._tail + n) % self.capacity
            self._count -= n
            self._pending = 0
