"""Shard worker entrypoint: one :class:`EvalServer` hosting one shard.

The slow fleet drills run workers as real processes::

    python -m metrics_tpu.serve.worker --shard 1 --num-shards 4 \
        --num-streams 64 --checkpoint-root /tmp/fleet-ckpt

The worker builds the SAME per-shard registry :class:`LocalFleet` would
build for that shard (``mse`` plain job + ``per_tenant`` multistream job —
the drill vocabulary from ``serve.soak``), binds an ephemeral port unless
``--port`` is given, prints ``READY <port>`` on stdout for the parent to
scrape, and serves until SIGTERM/SIGINT.  The coordinator talks to it
through :class:`~metrics_tpu.serve.coordinator.HTTPShard`; a kill -9 →
respawn of this process is the fleet failover drill.
"""
# analyze: skip-file[serve-blocking] -- process entrypoint: owns worker
# construction and the final drain/checkpoint on shutdown, like the fleet
# layer it mirrors.

from __future__ import annotations

import argparse
import signal
import threading
from typing import List, Optional, Sequence

from metrics_tpu.serve.fleet import (
    FleetSpec,
    JobSpec,
    build_router,
    build_shard_registry,
)
from metrics_tpu.serve.server import EvalServer, ServeConfig

__all__ = ["drill_jobs", "run_worker", "main"]


def drill_jobs(num_streams: int) -> List[JobSpec]:
    """The fleet drill vocabulary: one plain job, one multistream job."""
    from metrics_tpu.regression import MeanSquaredError

    return [
        JobSpec("mse", MeanSquaredError, num_streams=None),
        JobSpec("per_tenant", MeanSquaredError, num_streams=int(num_streams)),
    ]


def run_worker(
    shard: int,
    num_shards: int,
    num_streams: int = 64,
    host: str = "127.0.0.1",
    port: int = 0,
    checkpoint_root: Optional[str] = None,
    block_rows: int = 64,
    flush_interval: float = 3600.0,
    max_staleness: Optional[float] = None,
    wal_exactly_once: bool = False,
) -> EvalServer:
    """Build shard ``shard``'s registry and start its server (started).

    ``wal_exactly_once`` arms the worker side of the durable-ingest
    protocol: seq-tagged frame dedup on ``/ingest_columns``, applied-seq
    watermarks in every checkpoint, and ``wal_marks`` on ``/healthz`` —
    the frontend (or a soak driver) owns the WalWriter itself.
    """
    spec = FleetSpec(
        num_shards=int(num_shards),
        jobs=drill_jobs(num_streams),
        checkpoint_root=checkpoint_root,
        server_config=ServeConfig(
            host=host,
            port=int(port),
            block_rows=int(block_rows),
            # large interval = no wall-clock forcing: dispatch boundaries
            # stay a pure function of row count (the bitwise-drill contract)
            flush_interval=float(flush_interval),
            wal_exactly_once=bool(wal_exactly_once),
        ),
        max_staleness=max_staleness,
    )
    router = build_router(spec)
    registry = build_shard_registry(spec, int(shard), router)
    manager = None
    if checkpoint_root is not None:
        from metrics_tpu.checkpoint.manager import (
            CheckpointManager,
            shard_checkpoint_directory,
        )

        manager = CheckpointManager(
            directory=shard_checkpoint_directory(checkpoint_root, int(shard)),
            max_staleness=max_staleness,
        )
    server = EvalServer(
        registry,
        config=spec.server_config,
        checkpoint_manager=manager,
        # builders arm /migrate_in: a subprocess worker can adopt spans
        # during an elastic resize just like an in-process shard
        builders={job.name: job for job in spec.jobs},
    )
    return server.start()


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m metrics_tpu.serve.worker",
        description="one shard worker of the sharded serve fleet",
    )
    parser.add_argument("--shard", type=int, required=True)
    parser.add_argument("--num-shards", type=int, required=True)
    parser.add_argument("--num-streams", type=int, default=64)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--checkpoint-root", default=None)
    parser.add_argument("--block-rows", type=int, default=64)
    parser.add_argument("--flush-interval", type=float, default=3600.0)
    parser.add_argument("--max-staleness", type=float, default=None)
    parser.add_argument(
        "--wal-exactly-once",
        action="store_true",
        help="seq-dedup framed ingest and checkpoint applied-seq watermarks",
    )
    args = parser.parse_args(argv)

    server = run_worker(
        shard=args.shard,
        num_shards=args.num_shards,
        num_streams=args.num_streams,
        host=args.host,
        port=args.port,
        checkpoint_root=args.checkpoint_root,
        block_rows=args.block_rows,
        flush_interval=args.flush_interval,
        max_staleness=args.max_staleness,
        wal_exactly_once=args.wal_exactly_once,
    )
    print(f"READY {server.port}", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda _s, _f: stop.set())
    while not stop.wait(timeout=0.2):
        pass
    # graceful drain; the final checkpoint only exists with a manager
    server.stop(final_checkpoint=args.checkpoint_root is not None)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
