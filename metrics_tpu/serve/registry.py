"""Named evaluation jobs: the serve layer's metric registry.

An :class:`EvalJob` pairs one metric instance with a name, a lock, and the
export/query policy the HTTP surface needs; a :class:`MetricRegistry` is the
ordered collection of jobs one server hosts.  Three invariants the registry
enforces so request threads can never stall on the network:

* **No collectives on read paths.**  Registration forces
  ``sync_on_compute = False`` on every job metric — computes and stream
  queries read local state only.  Cross-host aggregation is the scraper's
  job (every replica exports its own gauges), or an explicit operator-driven
  ``sync`` outside the request path.
* **Single-writer state.**  All metric state mutation happens on the
  ingestion consumer thread; every state read (compute, query, export,
  checkpoint encode) takes the per-job lock, so HTTP threads and the
  durability loop never race the consumer.
* **Bounded exports.**  ``export_values`` never materializes a multistream
  job's full ``(num_streams, ...)`` value vector on the host: per-tenant
  jobs export aggregate gauges (active/dropped stream counts) plus an
  optional device-ranked ``top_k`` slice chosen at registration.
"""

from __future__ import annotations

import re
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric
from metrics_tpu.multistream import MultiStreamMetric
from metrics_tpu.obs import core as _obs
from metrics_tpu.streaming import TimeDecayedMetric, WindowedMetric
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["EvalJob", "MetricRegistry"]

_JOB_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.:-]*$")

# predicate vocabulary for the HTTP `where` query (op, threshold) — kept as a
# table so the handler never eval()s anything
_WHERE_OPS: Dict[str, Callable[[Any, float], Any]] = {
    "gt": lambda v, t: v > t,
    "ge": lambda v, t: v >= t,
    "lt": lambda v, t: v < t,
    "le": lambda v, t: v <= t,
}


def _nested_floats(arr: np.ndarray) -> Any:
    # hand-rolled .tolist(): serve/ rides the shape_lint gate, which bans the
    # host-pull spellings outright rather than reasoning about context
    if arr.ndim == 0:
        return float(arr)
    return [_nested_floats(row) for row in arr]


def _to_jsonable(value: Any) -> Any:
    """Host-side conversion of a computed value to JSON-friendly data."""
    if isinstance(value, dict):
        return {str(k): _to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    return _nested_floats(np.asarray(value, np.float64))


class EvalJob:
    """One named evaluation job: a metric, its lock, and its export policy.

    Args:
        name: registry key (also the ``job`` label on exported gauges).
        metric: the metric instance that accumulates this job's records.
        components: optional names for the elements of a vector-valued
            compute (e.g. ``("p50", "p99")`` for a two-quantile
            ``StreamingQuantile``); used as the ``component`` gauge label.
        export_top_k: for multistream jobs, export the k highest-valued
            streams as per-stream gauges (0 = aggregate gauges only).
        export_key: ``key=`` passed to the multistream ranking when the base
            compute is a dict/vector.
    """

    def __init__(
        self,
        name: str,
        metric: Metric,
        components: Optional[Sequence[str]] = None,
        export_top_k: int = 0,
        export_key: Any = None,
    ) -> None:
        self.name = name
        self.metric = metric
        self.components = None if components is None else tuple(str(c) for c in components)
        self.export_top_k = int(export_top_k)
        self.export_key = export_key
        # RLock: a checkpoint encode under the registry-wide lock sweep may
        # re-enter through metric hooks that take the same job's lock
        self.lock = threading.RLock()
        try:  # named in the runtime lock-witness graph; raw RLocks reject attrs
            self.lock.witness_name = f"EvalJob[{name}].lock"
        except AttributeError:
            pass
        self.records_ingested = 0  # host counter, consumer thread only
        self.blocks_dispatched = 0

    # ------------------------------------------------------------- inspection
    @property
    def is_multistream(self) -> bool:
        return isinstance(self.metric, MultiStreamMetric)

    @property
    def kind(self) -> str:
        if self.is_multistream:
            return "multistream"
        if isinstance(self.metric, WindowedMetric):
            return "windowed"
        if isinstance(self.metric, TimeDecayedMetric):
            return "time_decayed"
        return "plain"

    # ------------------------------------------------------------ state reads
    def compute(self) -> Any:
        """The job's computed value (device/host mix as the metric returns)."""
        with self.lock:
            return self.metric.compute()

    def compute_streams(self, stream_ids: Sequence[int]) -> Any:
        if not self.is_multistream:
            raise MetricsTPUUserError(
                f"job {self.name!r} is {self.kind}; per-stream reads need a "
                "MultiStreamMetric job"
            )
        with self.lock:
            return self.metric.compute_streams(np.asarray(list(stream_ids), np.int32))

    def top_k(self, k: int, key: Any = None, largest: bool = True) -> Tuple[Any, Any]:
        if not self.is_multistream:
            raise MetricsTPUUserError(
                f"job {self.name!r} is {self.kind}; stream ranking needs a "
                "MultiStreamMetric job"
            )
        with self.lock:
            return self.metric.top_k(k, key=key, largest=largest)

    def where_op(self, op: str, threshold: float, k: int, key: Any = None) -> Tuple[Any, Any]:
        """``where`` with a named comparison op — the HTTP-safe predicate."""
        if not self.is_multistream:
            raise MetricsTPUUserError(
                f"job {self.name!r} is {self.kind}; stream filtering needs a "
                "MultiStreamMetric job"
            )
        if op not in _WHERE_OPS:
            raise MetricsTPUUserError(
                f"unknown where-op {op!r}; expected one of {sorted(_WHERE_OPS)}"
            )
        fn = _WHERE_OPS[op]
        thr = float(threshold)
        with self.lock:
            return self.metric.where(lambda v: fn(v, thr), k=k, key=key)

    def advance_window(self) -> int:
        """Rotate a windowed job's ring (no-op guard for other kinds)."""
        if not isinstance(self.metric, WindowedMetric):
            raise MetricsTPUUserError(
                f"job {self.name!r} is {self.kind}; only windowed jobs advance"
            )
        with self.lock:
            return self.metric.advance()

    # --------------------------------------------------------------- exports
    def export_values(self) -> Any:
        """This job's gauge payload for ``metric_values_prometheus_text``.

        Scalar computes export as one gauge; dict computes as ``component``-
        labeled gauges; small vectors by ``components`` name (or index).
        Multistream jobs export ``active_streams`` / ``dropped_rows``
        aggregates plus, when ``export_top_k`` is set, the top-k streams as
        ``stream``-labeled gauges — never the full per-stream vector.
        """
        with self.lock:
            if self.is_multistream:
                out: List[Tuple[Dict[str, str], float]] = [
                    ({"component": "active_streams"}, float(self.metric.active_streams())),
                    ({"component": "dropped_rows"}, float(self.metric.dropped_rows())),
                ]
                if self.export_top_k > 0:
                    k = min(self.export_top_k, self.metric.num_streams)
                    values, ids = self.metric.top_k(k, key=self.export_key)
                    values = np.asarray(values, np.float64)
                    ids = np.asarray(ids)
                    for v, i in zip(values, ids):
                        out.append(({"stream": str(int(i))}, float(v)))
                return out
            value = self.metric.compute()
        if isinstance(value, dict):
            return {str(k): float(np.asarray(v)) for k, v in value.items()}
        arr = np.asarray(value, np.float64)
        if arr.ndim == 0:
            return float(arr)
        flat = arr.reshape(-1)
        names = self.components or [str(i) for i in range(flat.shape[0])]
        if len(names) != flat.shape[0]:
            raise MetricsTPUUserError(
                f"job {self.name!r} declares {len(names)} component name(s) for a "
                f"compute of {flat.shape[0]} element(s)"
            )
        return {name: float(v) for name, v in zip(names, flat)}


class MetricRegistry:
    """Ordered name -> :class:`EvalJob` map; the unit a server hosts and a
    :class:`~metrics_tpu.checkpoint.CheckpointManager` snapshots."""

    def __init__(self) -> None:
        self._jobs: Dict[str, EvalJob] = {}
        self._ckpt_target: Optional[MetricCollection] = None

    def register(
        self,
        name: str,
        metric: Metric,
        components: Optional[Sequence[str]] = None,
        export_top_k: int = 0,
        export_key: Any = None,
    ) -> EvalJob:
        """Add a job.  Forces ``sync_on_compute = False`` on the metric (the
        no-collectives-on-read invariant) and rejects duplicate names.

        Also forces ``lazy_updates = 0``: the ingestion path already
        micro-batches records into fixed-shape blocks, so metric-level lazy
        accumulation on top buys no dispatches — but its flush program is
        compiled per distinct pending *count*, which would make the first
        query after a burst pay a fresh XLA compile (hundreds of ms of p99)
        instead of a traced read.
        """
        if not isinstance(metric, Metric):
            raise MetricsTPUUserError(
                f"job {name!r} needs a Metric instance, got {type(metric).__name__}"
            )
        if not _JOB_NAME_RE.match(name or ""):
            raise MetricsTPUUserError(
                f"job name {name!r} is not a valid label value; use letters, "
                "digits, and [_.:-]"
            )
        if name in self._jobs:
            raise MetricsTPUUserError(f"job name {name!r} already registered")
        # request threads read local state only; see the module docstring
        metric.sync_on_compute = False
        metric.dist_sync_on_step = False
        # blocks are the batching unit here: fold each dispatch immediately
        # so query-time flushes never compile count-dependent scan programs
        metric.lazy_updates = 0
        job = EvalJob(
            name,
            metric,
            components=components,
            export_top_k=export_top_k,
            export_key=export_key,
        )
        self._jobs[name] = job
        self._ckpt_target = None
        _obs.counter_inc("serve.jobs_registered", metric=type(metric).__name__)
        return job

    def rebind(self, name: str, metric: Metric) -> EvalJob:
        """Swap a registered job's metric instance in place (elastic resize).

        The migration commit point on a worker: the staged post-resize
        metric replaces the live one under the job lock, so every reader
        (compute, export, checkpoint encode, batcher flush) sees either the
        old state or the new state, never a mix.  The same serve invariants
        ``register`` stamps are re-forced on the incoming metric, and the
        cached checkpoint target is invalidated so the next snapshot encodes
        the new instance.
        """
        if not isinstance(metric, Metric):
            raise MetricsTPUUserError(
                f"job {name!r} needs a Metric instance, got {type(metric).__name__}"
            )
        job = self[name]
        metric.sync_on_compute = False
        metric.dist_sync_on_step = False
        metric.lazy_updates = 0
        with job.lock:
            job.metric = metric
            self._ckpt_target = None
        return job

    def unregister(self, name: str) -> EvalJob:
        """Remove a job (shard retirement after its state migrated away).

        Taken under the job lock so an in-flight read finishes against the
        old instance; the cached checkpoint target is invalidated so later
        snapshots stop encoding the departed job.
        """
        job = self[name]
        with job.lock:
            del self._jobs[name]
            self._ckpt_target = None
        return job

    # -------------------------------------------------------------- dict-ish
    def __getitem__(self, name: str) -> EvalJob:
        try:
            return self._jobs[name]
        except KeyError:
            raise KeyError(
                f"unknown job {name!r}; registered: {sorted(self._jobs)}"
            ) from None

    def __contains__(self, name: str) -> bool:
        return name in self._jobs

    def __iter__(self) -> Iterator[str]:
        return iter(self._jobs)

    def __len__(self) -> int:
        return len(self._jobs)

    def jobs(self) -> List[EvalJob]:
        return list(self._jobs.values())

    # ----------------------------------------------------------- bulk reads
    def compute_all(self) -> Dict[str, Any]:
        """Every job's computed value as JSON-friendly host data.  For
        multistream jobs this DOES materialize the full per-stream vector —
        it is the drill/debug path, not the scrape path."""
        return {name: _to_jsonable(job.compute()) for name, job in self._jobs.items()}

    def export_values(self) -> Dict[str, Any]:
        """The gauge payload ``obs.metric_values_prometheus_text`` renders."""
        return {name: job.export_values() for name, job in self._jobs.items()}

    def describe(self) -> List[Dict[str, Any]]:
        """Job inventory for ``/healthz``."""
        return [
            {
                "job": job.name,
                "kind": job.kind,
                "metric": type(job.metric).__name__,
                "records_ingested": job.records_ingested,
                "blocks_dispatched": job.blocks_dispatched,
            }
            for job in self._jobs.values()
        ]

    # ------------------------------------------------------------ durability
    def checkpoint_target(self) -> MetricCollection:
        """The registry as a checkpoint target: one ``MetricCollection`` over
        every job metric with compute-group sharing OFF — jobs are
        independent tenants and must never alias state."""
        if not self._jobs:
            raise MetricsTPUUserError("cannot checkpoint an empty registry")
        if self._ckpt_target is None:
            # construction mutates member metrics once (pending-update flush
            # + sync-policy stamping), so the one-time build takes the full
            # sweep; every later call returns the cached collection lock-free
            with self.locked():
                if self._ckpt_target is None:
                    self._ckpt_target = MetricCollection(
                        {name: job.metric for name, job in self._jobs.items()},
                        compute_groups=False,
                    )
        return self._ckpt_target

    def locked(self) -> "_AllJobsLocked":
        """Context manager holding EVERY job lock (sorted by name, so the
        multi-lock sweep cannot deadlock against single-lock holders) — the
        full quiesce, now only needed for restore (which rewrites every
        job's state in place)."""
        return _AllJobsLocked(self.jobs())

    def lock_for_checkpoint_key(self, key: str) -> Any:
        """The per-job lock for one :func:`flatten_target` checkpoint key
        (``"col/{name}"``) — the ``lock_for`` hook of
        :meth:`CheckpointManager.encode_target`, so a snapshot encode holds
        one job lock at a time instead of quiescing the registry."""
        from contextlib import nullcontext

        name = key.split("/", 1)[1] if key.startswith("col/") else key
        job = self._jobs.get(name)
        return job.lock if job is not None else nullcontext()


class _AllJobsLocked:
    def __init__(self, jobs: List[EvalJob]) -> None:
        self._jobs = sorted(jobs, key=lambda j: j.name)

    def __enter__(self) -> None:
        for job in self._jobs:
            job.lock.acquire()

    def __exit__(self, *exc: Any) -> None:
        for job in reversed(self._jobs):
            job.lock.release()
