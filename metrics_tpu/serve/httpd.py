"""HTTP surface: stdlib ``http.server``, three read endpoints + ingest.

No new dependencies and **no blocking collectives or KV waits on any
request thread** — ``tools/serve_lint.py`` enforces that statically, and the
registry's forced ``sync_on_compute=False`` enforces it dynamically.  The
server is a :class:`PooledHTTPServer` — a **bounded** worker pool instead
of ``ThreadingHTTPServer``'s thread-per-connection, so a connection flood
costs a 503 rather than unbounded thread spawn; handlers only ever take a
per-job lock around a local device read, so scrapes and queries stay
responsive while the consumer thread dispatches blocks.

Endpoints:

* ``GET /healthz`` — liveness + queue depth + job inventory (JSON; 503 while
  draining so load balancers stop routing before shutdown).
* ``GET /metrics`` — Prometheus exposition: the runtime counters/spans from
  ``obs.prometheus_text()`` **plus** computed metric values as gauges from
  ``obs.metric_values_prometheus_text(registry)``.
* ``GET /query`` — per-tenant reads: ``?job=NAME`` (full compute),
  ``&streams=1,2,3`` (O(k) per-stream slice), ``&top_k=5[&largest=0]
  [&key=...]`` (device-ranked), ``&where=gt:0.9&k=8`` (device-filtered).
* ``POST /ingest`` — JSON records ``{"job": ..., "records": [{"values":
  [...], "stream_id": ...}, ...]}``; full queues reject with 429.
* ``POST /ingest_columns`` — the fleet's columnar wire: one JSON header
  line, then raw little-endian column bytes; parsed with ``np.frombuffer``
  (no per-record objects) and enqueued as ONE
  :class:`~metrics_tpu.serve.ingest.ColumnBatch`.
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from metrics_tpu.obs import core as _obs
from metrics_tpu.obs.exporters import metric_values_prometheus_text, prometheus_text
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["PooledHTTPServer", "ServeHTTPServer", "make_http_server"]

_MAX_INGEST_BYTES = 8 << 20

_503_RAW = (
    b"HTTP/1.1 503 Service Unavailable\r\n"
    b"Content-Length: 0\r\nConnection: close\r\n\r\n"
)


class PooledHTTPServer(HTTPServer):
    """HTTP server dispatching connections to a bounded worker pool.

    ``ThreadingHTTPServer`` spawns one thread per connection with no upper
    bound — an ingest flood turns into thousands of threads before the
    queue's backpressure ever engages.  Here the accept loop hands each
    connection to a fixed pool through a bounded hand-off queue; when every
    worker is busy and the queue is full the connection gets an immediate
    raw 503 (load balancers retry elsewhere) instead of a growing backlog.

    ``serve.frontend_threads_busy`` counts pool high-water marks: it ticks
    each time the number of simultaneously busy workers reaches a new
    maximum, so a scrape shows the worst concurrency the pool absorbed
    (obs counters are monotone; there are no gauges to sample).
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(
        self,
        address: Tuple[str, int],
        handler_cls: Any,
        pool_threads: int = 8,
        backlog: int = 64,
    ) -> None:
        super().__init__(address, handler_cls)
        if int(pool_threads) < 1:
            raise MetricsTPUUserError(
                f"pool_threads must be >= 1, got {pool_threads}"
            )
        self._work_q: "queue.Queue[Tuple[Any, Any]]" = queue.Queue(
            maxsize=max(1, int(backlog))
        )
        self._pool_stop = threading.Event()
        self._busy_lock = threading.Lock()
        try:  # named in the runtime lock-witness graph
            self._busy_lock.witness_name = "PooledHTTPServer._busy_lock"
        except AttributeError:
            pass
        self._busy = 0
        self._busy_high_water = 0
        self._pool = [
            threading.Thread(
                target=self._worker, name=f"http-pool-{i}", daemon=True
            )
            for i in range(int(pool_threads))
        ]
        for t in self._pool:
            t.start()

    # ------------------------------------------------------------ accept side
    def process_request(self, request: Any, client_address: Any) -> None:
        try:
            self._work_q.put_nowait((request, client_address))
        except queue.Full:
            # saturated: fail fast with a raw 503 on the socket — the
            # handler machinery needs a worker we do not have
            _obs.counter_inc("serve.http_pool_rejections")
            try:
                request.sendall(_503_RAW)
            except OSError:
                pass
            self.shutdown_request(request)

    # ------------------------------------------------------------ worker side
    def _note_busy(self, delta: int) -> None:
        with self._busy_lock:
            self._busy += delta
            new_high = self._busy > self._busy_high_water
            if new_high:
                self._busy_high_water = self._busy
        if new_high:
            _obs.counter_inc("serve.frontend_threads_busy")

    def _worker(self) -> None:
        while not self._pool_stop.is_set():
            try:
                request, client_address = self._work_q.get(timeout=0.1)
            except queue.Empty:
                continue
            self._note_busy(1)
            try:
                self.finish_request(request, client_address)
            except Exception:  # noqa: BLE001 — one bad socket must not kill a worker
                self.handle_error(request, client_address)
            finally:
                self.shutdown_request(request)
                self._note_busy(-1)

    def server_close(self) -> None:
        self._pool_stop.set()
        for t in self._pool:
            t.join(timeout=5.0)
        super().server_close()


class ServeHTTPServer(PooledHTTPServer):
    """Pooled HTTP server carrying the owning EvalServer reference."""

    def __init__(
        self,
        address: Tuple[str, int],
        eval_server: Any,
        pool_threads: int = 8,
        backlog: int = 64,
    ) -> None:
        super().__init__(
            address, _Handler, pool_threads=pool_threads, backlog=backlog
        )
        self.eval_server = eval_server


def make_http_server(
    host: str,
    port: int,
    eval_server: Any,
    pool_threads: int = 8,
    backlog: int = 64,
) -> ServeHTTPServer:
    """Bind the serve endpoints; ``port=0`` picks an ephemeral port."""
    return ServeHTTPServer(
        (host, port), eval_server, pool_threads=pool_threads, backlog=backlog
    )


class _Handler(BaseHTTPRequestHandler):
    server_version = "metrics-tpu-serve/1.0"
    protocol_version = "HTTP/1.1"

    # ------------------------------------------------------------- plumbing
    def log_message(self, fmt: str, *args: Any) -> None:
        # request logging is the counters' job, not stderr's
        pass

    def _send(self, status: int, body: bytes, content_type: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: Dict[str, Any]) -> None:
        self._send(status, json.dumps(payload).encode(), "application/json")

    def _fail(self, status: int, message: str) -> None:
        _obs.counter_inc("serve.http_errors", status=str(status))
        self._send_json(status, {"error": message})

    # ------------------------------------------------------------- routing
    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        url = urlparse(self.path)
        try:
            if url.path == "/healthz":
                self._healthz()
            elif url.path == "/metrics":
                self._metrics()
            elif url.path == "/query":
                self._query(parse_qs(url.query))
            else:
                self._fail(404, f"no route {url.path!r}")
        except MetricsTPUUserError as err:
            self._fail(400, str(err))
        except BrokenPipeError:
            pass
        except Exception as err:  # one bad request must not kill the thread pool
            self._fail(500, f"{type(err).__name__}: {err}")

    def do_POST(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        try:
            if url.path == "/ingest":
                self._ingest()
            elif url.path == "/ingest_columns":
                self._ingest_columns()
            elif url.path == "/flush":
                self._flush(parse_qs(url.query))
            elif url.path == "/checkpoint":
                self._checkpoint()
            elif url.path == "/migrate_out":
                self._migrate_out()
            elif url.path == "/migrate_in":
                self._migrate_in()
            elif url.path == "/migrate_commit":
                self._migrate_commit()
            elif url.path == "/retire_job":
                self._retire_job()
            else:
                self._fail(404, f"no route {url.path!r}")
        except MetricsTPUUserError as err:
            self._fail(400, str(err))
        except BrokenPipeError:
            pass
        except Exception as err:
            self._fail(500, f"{type(err).__name__}: {err}")

    # ------------------------------------------------------------ endpoints
    def _healthz(self) -> None:
        srv = self.server.eval_server
        _obs.counter_inc("serve.healthz_requests")
        payload = srv.health()
        # anything but "serving" (draining, failed writer) is a 503 so load
        # balancers stop routing to a server that cannot apply records
        self._send_json(200 if payload["status"] == "serving" else 503, payload)

    def _metrics(self) -> None:
        srv = self.server.eval_server
        _obs.counter_inc("serve.scrapes")
        text = prometheus_text() + metric_values_prometheus_text(srv.registry)
        self._send(200, text.encode(), "text/plain; version=0.0.4")

    @staticmethod
    def _one(params: Dict[str, List[str]], name: str) -> Optional[str]:
        vals = params.get(name)
        return vals[-1] if vals else None

    def _query(self, params: Dict[str, List[str]]) -> None:
        srv = self.server.eval_server
        name = self._one(params, "job")
        if not name:
            raise MetricsTPUUserError("query needs ?job=NAME")
        try:
            job = srv.registry[name]
        except KeyError as err:
            self._fail(404, str(err))
            return
        _obs.counter_inc("serve.queries", job=name)
        key: Any = self._one(params, "key")
        if key is not None and key.lstrip("-").isdigit():
            key = int(key)
        out: Dict[str, Any] = {"job": name, "kind": job.kind}
        streams = self._one(params, "streams")
        top_k = self._one(params, "top_k")
        where = self._one(params, "where")
        from metrics_tpu.serve.registry import _to_jsonable  # local: no cycle at import

        if streams is not None:
            ids = [int(s) for s in streams.split(",") if s != ""]
            out["streams"] = ids
            out["values"] = _to_jsonable(job.compute_streams(ids))
        elif top_k is not None:
            largest = self._one(params, "largest") != "0"
            values, ids = job.top_k(int(top_k), key=key, largest=largest)
            out["top_k"] = _to_jsonable(values)
            out["stream_ids"] = [int(i) for i in _as_int_list(ids)]
            out["largest"] = largest
        elif where is not None:
            op, _, threshold = where.partition(":")
            k = int(self._one(params, "k") or "16")
            ids, total = job.where_op(op, float(threshold), k=k, key=key)
            out["stream_ids"] = [i for i in _as_int_list(ids) if i >= 0]
            out["total_matches"] = int(_scalar(total))
        else:
            out["value"] = _to_jsonable(job.compute())
        self._send_json(200, out)

    def _ingest(self) -> None:
        srv = self.server.eval_server
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0 or length > _MAX_INGEST_BYTES:
            raise MetricsTPUUserError(
                f"ingest needs a JSON body of 1..{_MAX_INGEST_BYTES} bytes"
            )
        try:
            payload = json.loads(self.rfile.read(length).decode())
        except (ValueError, UnicodeDecodeError) as err:
            raise MetricsTPUUserError(f"ingest body is not valid JSON: {err}")
        name = payload.get("job")
        records = payload.get("records")
        if not isinstance(name, str) or not isinstance(records, list):
            raise MetricsTPUUserError(
                'ingest body must be {"job": NAME, "records": [...]}'
            )
        if name not in srv.registry:
            self._fail(404, f"unknown job {name!r}")
            return
        # validate the WHOLE batch before enqueuing any of it: a malformed
        # record mid-list must 400 with nothing accepted, not after earlier
        # records already landed with no accounting of which ones
        parsed: List[Tuple[Tuple[Any, ...], Optional[int]]] = []
        for i, rec in enumerate(records):
            if not isinstance(rec, dict):
                raise MetricsTPUUserError(
                    f"record {i} must be a JSON object, got {type(rec).__name__}"
                )
            values = rec.get("values")
            if not isinstance(values, list) or not values:
                raise MetricsTPUUserError(f'record {i} needs "values": [...]')
            stream_id = rec.get("stream_id")
            if stream_id is not None and (
                isinstance(stream_id, bool) or not isinstance(stream_id, int)
            ):
                raise MetricsTPUUserError(
                    f'record {i} has a non-integer "stream_id": {stream_id!r}'
                )
            parsed.append((tuple(values), stream_id))
        accepted = rejected = 0
        for values, stream_id in parsed:
            ok = srv.submit(name, values, stream_id=stream_id)
            accepted += int(ok)
            rejected += int(not ok)
        status = 429 if rejected and not accepted else 200
        self._send_json(status, {"accepted": accepted, "rejected": rejected})

    def _ingest_columns(self) -> None:
        """Columnar wire: ``<json header>\\n<raw column bytes>``.

        The header is ``{"job": NAME, "rows": n, "arity": k,
        "dtype": "<f4", "ids": bool}``; the payload is ``arity``
        column blobs of ``n`` rows each, then (when ``ids``) one int32
        blob of ``n`` stream ids.  Columns become ``np.frombuffer`` views
        over the request body — no per-record Python objects anywhere on
        this path — and enqueue as ONE ColumnBatch (one queue slot).

        An optional ``"seqs": [[seq_or_null, rows], ...]`` header field
        partitions the rows into WAL frames (they must sum to ``rows``):
        the server then seq-dedups per frame, so duplicated forwards —
        a coordinator POST retry, or a failover replay racing parked rows
        — land exactly once (a deduped frame still counts as accepted:
        idempotent success, not rejection).
        """
        import numpy as np

        srv = self.server.eval_server
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0 or length > _MAX_INGEST_BYTES:
            raise MetricsTPUUserError(
                f"ingest_columns needs a body of 1..{_MAX_INGEST_BYTES} bytes"
            )
        body = self.rfile.read(length)
        nl = body.find(b"\n")
        if nl < 0:
            raise MetricsTPUUserError(
                "ingest_columns body needs a JSON header line"
            )
        try:
            header = json.loads(body[:nl].decode())
        except (ValueError, UnicodeDecodeError) as err:
            raise MetricsTPUUserError(f"bad ingest_columns header: {err}")
        name = header.get("job")
        rows = header.get("rows")
        arity = header.get("arity")
        if (
            not isinstance(name, str)
            or not isinstance(rows, int)
            or not isinstance(arity, int)
            or isinstance(rows, bool)
            or isinstance(arity, bool)
            or rows < 1
            or arity < 1
        ):
            raise MetricsTPUUserError(
                'ingest_columns header needs {"job", "rows" >= 1, "arity" >= 1}'
            )
        if name not in srv.registry:
            self._fail(404, f"unknown job {name!r}")
            return
        try:
            dtype = np.dtype(header.get("dtype", "<f4"))
        except TypeError as err:
            raise MetricsTPUUserError(f"bad ingest_columns dtype: {err}")
        with_ids = bool(header.get("ids", False))
        col_bytes = rows * dtype.itemsize
        need = nl + 1 + arity * col_bytes + (rows * 4 if with_ids else 0)
        if need != length:
            raise MetricsTPUUserError(
                f"ingest_columns header declares {need} bytes, body has {length}"
            )
        offset = nl + 1
        cols = []
        for _ in range(arity):
            cols.append(
                np.frombuffer(body, dtype=dtype, count=rows, offset=offset)
            )
            offset += col_bytes
        stream_ids = (
            np.frombuffer(body, dtype="<i4", count=rows, offset=offset)
            if with_ids
            else None
        )
        seqs = header.get("seqs")
        if seqs is not None:
            if not isinstance(seqs, list) or not all(
                isinstance(s, list)
                and len(s) == 2
                and (s[0] is None or isinstance(s[0], int))
                and isinstance(s[1], int)
                and not isinstance(s[1], bool)
                and s[1] >= 1
                for s in seqs
            ):
                raise MetricsTPUUserError(
                    'ingest_columns "seqs" must be [[seq_or_null, rows>=1], ...]'
                )
            if sum(s[1] for s in seqs) != rows:
                raise MetricsTPUUserError(
                    'ingest_columns "seqs" row counts must sum to "rows"'
                )
            seqs = [(s[0], s[1]) for s in seqs]
        ok = srv.submit_columns(name, tuple(cols), stream_ids=stream_ids, seqs=seqs)
        _obs.counter_inc("serve.column_batches", job=name)
        status = 200 if ok else 429
        self._send_json(
            status,
            {"accepted": rows if ok else 0, "rejected": 0 if ok else rows},
        )

    def _flush(self, params: Dict[str, List[str]]) -> None:
        """Drain the ingest queue + dispatch all staged rows (fleet drills
        call this on each shard before a coordinated read or checkpoint)."""
        srv = self.server.eval_server
        timeout = float(self._one(params, "timeout") or "10.0")
        ok = srv.flush(timeout=timeout)
        self._send_json(200 if ok else 504, {"flushed": bool(ok)})

    def _checkpoint(self) -> None:
        """Operator-triggered durable snapshot (the coordinator's failover
        drill checkpoints a shard before killing it).  ``wal_marks`` in the
        response are the applied-seq watermarks the commit recorded — the
        fleet truncates WAL segments they cover."""
        srv = self.server.eval_server
        step = srv.checkpoint_now()
        out: Dict[str, Any] = {"step": int(step)}
        marks = getattr(srv, "last_checkpoint_wal_marks", None)
        if marks is not None:
            out["wal_marks"] = dict(marks)
        self._send_json(200, out)

    # ------------------------------------------------- elastic resize wire
    def _json_body(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0 or length > _MAX_INGEST_BYTES:
            raise MetricsTPUUserError(
                f"endpoint needs a JSON body of 1..{_MAX_INGEST_BYTES} bytes"
            )
        try:
            payload = json.loads(self.rfile.read(length).decode())
        except (ValueError, UnicodeDecodeError) as err:
            raise MetricsTPUUserError(f"body is not valid JSON: {err}")
        if not isinstance(payload, dict) or not isinstance(payload.get("job"), str):
            raise MetricsTPUUserError('body must be a JSON object with "job"')
        return payload

    def _migrate_out(self) -> None:
        """Export migrating state for one job (coordinator resize, donor
        side): a pure read — the donor keeps serving from its live state."""
        srv = self.server.eval_server
        payload = self._json_body()
        out = srv.export_span(
            payload["job"], lo=payload.get("lo"), hi=payload.get("hi")
        )
        _obs.counter_inc("serve.migrate_out_requests", job=payload["job"])
        self._send_json(200, out)

    def _migrate_in(self) -> None:
        """Stage a job's post-resize metric from donor pieces (recipient
        side).  Nothing goes live until ``/migrate_commit``."""
        srv = self.server.eval_server
        payload = self._json_body()
        pieces = payload.get("pieces")
        if not isinstance(pieces, list) or not pieces:
            raise MetricsTPUUserError('migrate_in needs "pieces": [...]')
        adopted = srv.import_span(
            payload["job"],
            width=payload.get("width"),
            span_lo=int(payload.get("span_lo", 0)),
            pieces=tuple(pieces),
            plain=bool(payload.get("plain", False)),
        )
        self._send_json(200, {"job": payload["job"], "adopted": int(adopted)})

    def _migrate_commit(self) -> None:
        """Flip one job to its staged post-resize metric — or, with
        ``"discard": true``, drop staged state (the coordinator's abort)."""
        srv = self.server.eval_server
        payload = self._json_body()
        if payload.get("discard"):
            dropped = srv.discard_migration(payload["job"])
            self._send_json(200, {"job": payload["job"], "discarded": dropped})
            return
        srv.commit_migration(payload["job"])
        self._send_json(200, {"job": payload["job"], "committed": True})

    def _retire_job(self) -> None:
        """Drop a job whose state migrated away (plain-job donor)."""
        srv = self.server.eval_server
        payload = self._json_body()
        srv.retire_job(payload["job"])
        self._send_json(200, {"job": payload["job"], "retired": True})


def _as_int_list(arr: Any) -> List[int]:
    import numpy as np

    return [int(v) for v in np.asarray(arr).reshape(-1)]


def _scalar(value: Any) -> float:
    import numpy as np

    return float(np.asarray(value))
