"""HTTP surface: stdlib ``http.server``, three read endpoints + ingest.

No new dependencies and **no blocking collectives or KV waits on any
request thread** — ``tools/serve_lint.py`` enforces that statically, and the
registry's forced ``sync_on_compute=False`` enforces it dynamically.  The
server is a ``ThreadingHTTPServer``: scrapes and queries stay responsive
while the consumer thread dispatches blocks, because handlers only ever
take a per-job lock around a local device read.

Endpoints:

* ``GET /healthz`` — liveness + queue depth + job inventory (JSON; 503 while
  draining so load balancers stop routing before shutdown).
* ``GET /metrics`` — Prometheus exposition: the runtime counters/spans from
  ``obs.prometheus_text()`` **plus** computed metric values as gauges from
  ``obs.metric_values_prometheus_text(registry)``.
* ``GET /query`` — per-tenant reads: ``?job=NAME`` (full compute),
  ``&streams=1,2,3`` (O(k) per-stream slice), ``&top_k=5[&largest=0]
  [&key=...]`` (device-ranked), ``&where=gt:0.9&k=8`` (device-filtered).
* ``POST /ingest`` — JSON records ``{"job": ..., "records": [{"values":
  [...], "stream_id": ...}, ...]}``; full queues reject with 429.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from metrics_tpu.obs import core as _obs
from metrics_tpu.obs.exporters import metric_values_prometheus_text, prometheus_text
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["ServeHTTPServer", "make_http_server"]

_MAX_INGEST_BYTES = 8 << 20


class ServeHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the owning EvalServer reference."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address: Tuple[str, int], eval_server: Any) -> None:
        super().__init__(address, _Handler)
        self.eval_server = eval_server


def make_http_server(host: str, port: int, eval_server: Any) -> ServeHTTPServer:
    """Bind the serve endpoints; ``port=0`` picks an ephemeral port."""
    return ServeHTTPServer((host, port), eval_server)


class _Handler(BaseHTTPRequestHandler):
    server_version = "metrics-tpu-serve/1.0"
    protocol_version = "HTTP/1.1"

    # ------------------------------------------------------------- plumbing
    def log_message(self, fmt: str, *args: Any) -> None:
        # request logging is the counters' job, not stderr's
        pass

    def _send(self, status: int, body: bytes, content_type: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: Dict[str, Any]) -> None:
        self._send(status, json.dumps(payload).encode(), "application/json")

    def _fail(self, status: int, message: str) -> None:
        _obs.counter_inc("serve.http_errors", status=str(status))
        self._send_json(status, {"error": message})

    # ------------------------------------------------------------- routing
    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        url = urlparse(self.path)
        try:
            if url.path == "/healthz":
                self._healthz()
            elif url.path == "/metrics":
                self._metrics()
            elif url.path == "/query":
                self._query(parse_qs(url.query))
            else:
                self._fail(404, f"no route {url.path!r}")
        except MetricsTPUUserError as err:
            self._fail(400, str(err))
        except BrokenPipeError:
            pass
        except Exception as err:  # one bad request must not kill the thread pool
            self._fail(500, f"{type(err).__name__}: {err}")

    def do_POST(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        try:
            if url.path == "/ingest":
                self._ingest()
            else:
                self._fail(404, f"no route {url.path!r}")
        except MetricsTPUUserError as err:
            self._fail(400, str(err))
        except BrokenPipeError:
            pass
        except Exception as err:
            self._fail(500, f"{type(err).__name__}: {err}")

    # ------------------------------------------------------------ endpoints
    def _healthz(self) -> None:
        srv = self.server.eval_server
        _obs.counter_inc("serve.healthz_requests")
        payload = srv.health()
        # anything but "serving" (draining, failed writer) is a 503 so load
        # balancers stop routing to a server that cannot apply records
        self._send_json(200 if payload["status"] == "serving" else 503, payload)

    def _metrics(self) -> None:
        srv = self.server.eval_server
        _obs.counter_inc("serve.scrapes")
        text = prometheus_text() + metric_values_prometheus_text(srv.registry)
        self._send(200, text.encode(), "text/plain; version=0.0.4")

    @staticmethod
    def _one(params: Dict[str, List[str]], name: str) -> Optional[str]:
        vals = params.get(name)
        return vals[-1] if vals else None

    def _query(self, params: Dict[str, List[str]]) -> None:
        srv = self.server.eval_server
        name = self._one(params, "job")
        if not name:
            raise MetricsTPUUserError("query needs ?job=NAME")
        try:
            job = srv.registry[name]
        except KeyError as err:
            self._fail(404, str(err))
            return
        _obs.counter_inc("serve.queries", job=name)
        key: Any = self._one(params, "key")
        if key is not None and key.lstrip("-").isdigit():
            key = int(key)
        out: Dict[str, Any] = {"job": name, "kind": job.kind}
        streams = self._one(params, "streams")
        top_k = self._one(params, "top_k")
        where = self._one(params, "where")
        from metrics_tpu.serve.registry import _to_jsonable  # local: no cycle at import

        if streams is not None:
            ids = [int(s) for s in streams.split(",") if s != ""]
            out["streams"] = ids
            out["values"] = _to_jsonable(job.compute_streams(ids))
        elif top_k is not None:
            largest = self._one(params, "largest") != "0"
            values, ids = job.top_k(int(top_k), key=key, largest=largest)
            out["top_k"] = _to_jsonable(values)
            out["stream_ids"] = [int(i) for i in _as_int_list(ids)]
            out["largest"] = largest
        elif where is not None:
            op, _, threshold = where.partition(":")
            k = int(self._one(params, "k") or "16")
            ids, total = job.where_op(op, float(threshold), k=k, key=key)
            out["stream_ids"] = [i for i in _as_int_list(ids) if i >= 0]
            out["total_matches"] = int(_scalar(total))
        else:
            out["value"] = _to_jsonable(job.compute())
        self._send_json(200, out)

    def _ingest(self) -> None:
        srv = self.server.eval_server
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0 or length > _MAX_INGEST_BYTES:
            raise MetricsTPUUserError(
                f"ingest needs a JSON body of 1..{_MAX_INGEST_BYTES} bytes"
            )
        try:
            payload = json.loads(self.rfile.read(length).decode())
        except (ValueError, UnicodeDecodeError) as err:
            raise MetricsTPUUserError(f"ingest body is not valid JSON: {err}")
        name = payload.get("job")
        records = payload.get("records")
        if not isinstance(name, str) or not isinstance(records, list):
            raise MetricsTPUUserError(
                'ingest body must be {"job": NAME, "records": [...]}'
            )
        if name not in srv.registry:
            self._fail(404, f"unknown job {name!r}")
            return
        # validate the WHOLE batch before enqueuing any of it: a malformed
        # record mid-list must 400 with nothing accepted, not after earlier
        # records already landed with no accounting of which ones
        parsed: List[Tuple[Tuple[Any, ...], Optional[int]]] = []
        for i, rec in enumerate(records):
            if not isinstance(rec, dict):
                raise MetricsTPUUserError(
                    f"record {i} must be a JSON object, got {type(rec).__name__}"
                )
            values = rec.get("values")
            if not isinstance(values, list) or not values:
                raise MetricsTPUUserError(f'record {i} needs "values": [...]')
            stream_id = rec.get("stream_id")
            if stream_id is not None and (
                isinstance(stream_id, bool) or not isinstance(stream_id, int)
            ):
                raise MetricsTPUUserError(
                    f'record {i} has a non-integer "stream_id": {stream_id!r}'
                )
            parsed.append((tuple(values), stream_id))
        accepted = rejected = 0
        for values, stream_id in parsed:
            ok = srv.submit(name, values, stream_id=stream_id)
            accepted += int(ok)
            rejected += int(not ok)
        status = 429 if rejected and not accepted else 200
        self._send_json(status, {"accepted": accepted, "rejected": rejected})


def _as_int_list(arr: Any) -> List[int]:
    import numpy as np

    return [int(v) for v in np.asarray(arr).reshape(-1)]


def _scalar(value: Any) -> float:
    import numpy as np

    return float(np.asarray(value))
