"""Fleet coordinator: one frontend over N shard workers.

The coordinator owns no metric state.  It holds a
:class:`~metrics_tpu.serve.router.ShardRouter` plus one opaque **handle**
per shard and does three things:

* **Parallel ingest** — request threads partition a batch's columns by
  shard (one vectorized ``searchsorted``) and copy them into per-
  ``(shard, job)`` :class:`~metrics_tpu.serve.columnar.ColumnRing`
  staging; one forwarder thread per shard drains ring **views** and ships
  them to its worker.  The HTTP thread never waits on a worker: a full
  ring is a counted rejection (backpressure), a dead worker just leaves
  rows parked in its ring until failover replaces the handle.
* **Scatter-gather queries** — ``top_k`` / ``where`` / ``compute`` fan
  out to every shard through a thread pool with **timed** result waits
  and merge on the coordinator.  Each worker already ranked its own span
  on device (``lax.top_k``), so the merge is O(k * num_shards) host work;
  contiguous ascending spans make the merged ranking bitwise identical to
  a single worker over the union of streams (ties break lowest-global-id
  first, NaNs sort as ``-inf``/``+inf`` exactly like the device kernel).
* **Liveness + failover** — ``health()`` rolls up per-shard probes (an
  unreachable worker marks the fleet degraded; the HTTP ``/healthz``
  answers 503) and ``failover(shard)`` swaps in a replacement handle from
  the injected ``respawn`` callback (the fleet layer restores the dead
  shard's checkpoint before handing the handle back).

Shard handles are **duck-typed** on purpose: the coordinator never
imports or constructs worker machinery, so ``tools/analyze``'s
serve-blocking and lock-order passes check this whole module with no
opt-outs — nothing on a request thread may block, and nothing here does.
A handle provides::

    ingest_columns(job, cols, stream_ids=None) -> bool
    ingest_rows(job, rows)                     -> (accepted, rejected)
    compute(job)                               -> jsonable
    compute_streams(job, local_ids)            -> jsonable list
    top_k(job, k, key, largest)                -> (values, local_ids)
    where(job, op, threshold, k, key)          -> (local_ids, total)
    health()                                   -> dict
    flush(timeout)                             -> bool

:class:`HTTPShard` (below) speaks that protocol to a remote worker's
HTTP surface; the in-process equivalent lives in
``metrics_tpu.serve.fleet``.
"""

from __future__ import annotations

import json
import math
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)
from urllib.error import HTTPError, URLError
from urllib.parse import parse_qs, urlparse
from urllib.request import Request, urlopen

import numpy as np

from metrics_tpu.obs import core as _obs
from metrics_tpu.obs.exporters import prometheus_text
from metrics_tpu.serve.columnar import ColumnRing
from metrics_tpu.serve.httpd import _MAX_INGEST_BYTES, PooledHTTPServer
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = [
    "HTTPShard",
    "FleetCoordinator",
    "FleetHTTPServer",
    "make_fleet_http_server",
]

_FORWARD_POLL_S = 0.005  # forwarder idle poll (timed waits only)
_FORWARD_IDLE_MAX_S = 0.08  # idle backoff cap: keeps N sleeping forwarders
# from preempting request threads every few ms on small hosts


def _is_scalar(value: Any) -> bool:
    return isinstance(value, (int, float)) and not isinstance(value, bool)


class HTTPShard:
    """Handle over one remote shard worker's HTTP surface.

    Every method is one request/response with a bounded socket timeout;
    nothing here takes a lock, so the lock-order pass has nothing to
    order.  Ingest serializes ring views straight onto the columnar wire
    (``POST /ingest_columns``) — the worker reconstructs the columns with
    ``np.frombuffer``; no per-record objects on either side.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0) -> None:
        self.base = f"http://{host}:{int(port)}"
        self.timeout = float(timeout)

    # ------------------------------------------------------------- plumbing
    def _get(self, path: str) -> Dict[str, Any]:
        with urlopen(self.base + path, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode())

    def _post(self, path: str, body: bytes, content_type: str) -> Tuple[int, Dict[str, Any]]:
        req = Request(
            self.base + path,
            data=body,
            headers={"Content-Type": content_type},
            method="POST",
        )
        try:
            with urlopen(req, timeout=self.timeout) as resp:
                return resp.status, json.loads(resp.read().decode())
        except HTTPError as err:
            raw = err.read()
            try:
                payload = json.loads(raw.decode()) if raw else {}
            except ValueError:
                payload = {}
            return err.code, payload

    # --------------------------------------------------------------- ingest
    def ingest_columns(
        self,
        job: str,
        cols: Sequence[np.ndarray],
        stream_ids: Optional[np.ndarray] = None,
    ) -> bool:
        cols = [np.ascontiguousarray(c) for c in cols]
        header = {
            "job": job,
            "rows": int(cols[0].shape[0]),
            "arity": len(cols),
            "dtype": cols[0].dtype.str,
            "ids": stream_ids is not None,
        }
        parts = [json.dumps(header).encode(), b"\n"]
        parts.extend(c.tobytes() for c in cols)
        if stream_ids is not None:
            parts.append(np.ascontiguousarray(stream_ids, dtype="<i4").tobytes())
        status, _ = self._post(
            "/ingest_columns", b"".join(parts), "application/octet-stream"
        )
        return status == 200

    def ingest_rows(
        self, job: str, rows: Sequence[Tuple[Tuple[Any, ...], Optional[int]]]
    ) -> Tuple[int, int]:
        records = [
            {"values": list(values)}
            if stream_id is None
            else {"values": list(values), "stream_id": int(stream_id)}
            for values, stream_id in rows
        ]
        status, payload = self._post(
            "/ingest",
            json.dumps({"job": job, "records": records}).encode(),
            "application/json",
        )
        if status not in (200, 429):
            raise MetricsTPUUserError(
                f"shard {self.base} rejected ingest: HTTP {status} {payload}"
            )
        return int(payload.get("accepted", 0)), int(payload.get("rejected", 0))

    # ---------------------------------------------------------------- reads
    def compute(self, job: str) -> Any:
        return self._get(f"/query?job={job}")["value"]

    def compute_streams(self, job: str, local_ids: Sequence[int]) -> List[Any]:
        ids = ",".join(str(int(i)) for i in local_ids)
        return self._get(f"/query?job={job}&streams={ids}")["values"]

    def top_k(
        self, job: str, k: int, key: Any = None, largest: bool = True
    ) -> Tuple[List[float], List[int]]:
        path = f"/query?job={job}&top_k={int(k)}&largest={1 if largest else 0}"
        if key is not None:
            path += f"&key={key}"
        out = self._get(path)
        return out["top_k"], out["stream_ids"]

    def where(
        self, job: str, op: str, threshold: float, k: int, key: Any = None
    ) -> Tuple[List[int], int]:
        path = f"/query?job={job}&where={op}:{threshold!r}&k={int(k)}"
        if key is not None:
            path += f"&key={key}"
        out = self._get(path)
        return out["stream_ids"], int(out["total_matches"])

    # ------------------------------------------------------------ liveness
    def health(self) -> Dict[str, Any]:
        try:
            return self._get("/healthz")
        except HTTPError as err:  # worker answered 503 with a JSON body
            raw = err.read()
            try:
                return json.loads(raw.decode())
            except ValueError:
                return {"status": f"http_{err.code}"}

    def flush(self, timeout: float = 10.0) -> bool:
        status, payload = self._post(
            f"/flush?timeout={float(timeout)!r}", b"{}", "application/json"
        )
        return status == 200 and bool(payload.get("flushed"))

    def checkpoint(self) -> int:
        status, payload = self._post("/checkpoint", b"{}", "application/json")
        if status != 200:
            raise MetricsTPUUserError(
                f"shard {self.base} checkpoint failed: HTTP {status} {payload}"
            )
        return int(payload["step"])


class FleetCoordinator:
    """Routes ingest to shard rings and merges scatter-gather reads.

    Args:
        router: a built :class:`~metrics_tpu.serve.router.ShardRouter`.
        handles: one shard handle per shard, index-aligned with the
            router's shard ids (see the module docstring for the duck
            protocol).
        respawn: optional ``shard -> handle`` callback used by
            :meth:`failover`; the callback owns restoring the dead
            shard's checkpoint before returning the replacement.
        ring_capacity: rows per ``(shard, job)`` staging ring.
        ingest_dtype: dtype scalar JSON records are staged at (the
            columnar hot path; float32 halves the wire for serving).
        query_timeout: per-shard bound on every scatter-gather wait.
    """

    def __init__(
        self,
        router: Any,
        handles: Sequence[Any],
        respawn: Optional[Callable[[int], Any]] = None,
        ring_capacity: int = 8192,
        ingest_dtype: Any = np.float32,
        query_timeout: float = 30.0,
    ) -> None:
        if len(handles) != router.num_shards:
            raise MetricsTPUUserError(
                f"router expects {router.num_shards} shard(s), "
                f"got {len(handles)} handle(s)"
            )
        self.router = router
        self._handles: List[Any] = list(handles)
        self._respawn = respawn
        self.ring_capacity = int(ring_capacity)
        self.ingest_dtype = np.dtype(ingest_dtype)
        self.query_timeout = float(query_timeout)
        self._rings: Dict[Tuple[int, str], ColumnRing] = {}
        self._rings_lock = threading.Lock()
        try:  # named in the runtime lock-witness graph
            self._rings_lock.witness_name = "FleetCoordinator._rings_lock"
        except AttributeError:
            pass
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, len(self._handles)),
            thread_name_prefix="fleet-scatter",
        )
        self._stop = threading.Event()
        self._forwarders: List[threading.Thread] = []
        self._started = False

    # ---------------------------------------------------------------- lifecycle
    @property
    def num_shards(self) -> int:
        return len(self._handles)

    def handle(self, shard: int) -> Any:
        return self._handles[int(shard)]

    def start(self) -> "FleetCoordinator":
        """Spawn one forwarder thread per shard (idempotent)."""
        if self._started:
            return self
        self._started = True
        for shard in range(self.num_shards):
            t = threading.Thread(
                target=self._forward_loop,
                args=(shard,),
                name=f"fleet-forward-{shard}",
                daemon=True,
            )
            t.start()
            self._forwarders.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._forwarders:
            t.join(timeout=5.0)
        self._forwarders = []
        self._pool.shutdown(wait=False)

    # ------------------------------------------------------------------ ingest
    def _ring(self, shard: int, job: str, arity: int, with_ids: bool) -> ColumnRing:
        key = (int(shard), job)
        ring = self._rings.get(key)
        if ring is not None:
            return ring
        with self._rings_lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = ColumnRing(
                    arity,
                    capacity=self.ring_capacity,
                    with_ids=with_ids,
                    dtype=self.ingest_dtype,
                )
                self._rings[key] = ring
            return ring

    def _shard_rings(self, shard: int) -> List[Tuple[str, ColumnRing]]:
        # dict reads race benignly with _ring() inserts (GIL-atomic); a
        # ring missed this pass is drained on the next poll
        return [(job, r) for (s, job), r in list(self._rings.items()) if s == shard]

    def ingest_columns(
        self,
        job: str,
        cols: Sequence[np.ndarray],
        stream_ids: Optional[np.ndarray] = None,
    ) -> Tuple[int, int]:
        """Partition one columnar batch across shard rings.

        Returns ``(accepted, rejected)`` row counts; a rejection means the
        target shard's ring was full (its worker is slow or dead) — the
        caller sees backpressure immediately instead of queueing unbounded.
        """
        cols = [np.asarray(c).reshape(-1) for c in cols]
        n = int(cols[0].shape[0]) if cols else 0
        if n == 0:
            return 0, 0
        if self.router.is_multistream(job):
            if stream_ids is None:
                raise MetricsTPUUserError(
                    f"job {job!r} is multistream; ingest needs stream_ids"
                )
            parts = self.router.partition_ids(job, stream_ids)
            accepted = rejected = 0
            for shard, (positions, local_ids) in parts.items():
                ring = self._ring(shard, job, len(cols), with_ids=True)
                ok = ring.put([c[positions] for c in cols], local_ids)
                if ok:
                    accepted += int(positions.shape[0])
                else:
                    rejected += int(positions.shape[0])
            return accepted, rejected
        shard = self.router.owner(job)
        ring = self._ring(shard, job, len(cols), with_ids=False)
        ok = ring.put(cols, None)
        return (n, 0) if ok else (0, n)

    def ingest_records(
        self, job: str, records: Sequence[Tuple[Tuple[Any, ...], Optional[int]]]
    ) -> Tuple[int, int]:
        """Ingest parsed ``(values, stream_id)`` records.

        Scalar rows take the columnar hot path (one transpose into numpy,
        then :meth:`ingest_columns`); rows with array-valued fields fall
        back to per-shard record forwarding through ``handle.ingest_rows``.
        """
        if not records:
            return 0, 0
        multistream = self.router.is_multistream(job)
        scalar = all(
            all(_is_scalar(v) for v in values) for values, _sid in records
        )
        same_arity = len({len(values) for values, _sid in records}) == 1
        if multistream:
            missing = sum(1 for _v, sid in records if sid is None)
            if missing:
                _obs.counter_inc(
                    "serve.records_rejected", missing, reason="no_stream_id"
                )
                records = [r for r in records if r[1] is not None]
                if not records:
                    return 0, missing
        else:
            missing = 0
        accepted = rejected = 0
        if scalar and same_arity:
            arity = len(records[0][0])
            vals = np.asarray(
                [values for values, _sid in records], self.ingest_dtype
            )
            cols = [vals[:, i] for i in range(arity)]
            ids = (
                np.asarray([sid for _v, sid in records], np.int64)
                if multistream
                else None
            )
            accepted, rejected = self.ingest_columns(job, cols, ids)
            return accepted, rejected + missing
        # slow path: nested array values keep per-record framing
        by_shard: Dict[int, List[Tuple[Tuple[Any, ...], Optional[int]]]] = {}
        for values, sid in records:
            if multistream:
                shard, local = self.router.local_id(job, int(sid))
                by_shard.setdefault(shard, []).append((values, local))
            else:
                by_shard.setdefault(self.router.owner(job), []).append(
                    (values, None)
                )
        for shard, rows in by_shard.items():
            try:
                got, lost = self._handles[shard].ingest_rows(job, rows)
            except (OSError, URLError, MetricsTPUUserError):
                _obs.counter_inc(
                    "serve.fleet_forward_errors", shard=str(shard)
                )
                got, lost = 0, len(rows)
            accepted += got
            rejected += lost
        return accepted, rejected + missing

    def _forward_loop(self, shard: int) -> None:
        """Drain this shard's rings and ship views to the worker.

        A worker that rejects (429) or errors leaves the rows parked in
        the ring — ``commit(0)`` releases the drain without consuming, so
        the same rows retry after backoff (and survive a failover: the
        replacement handle picks them up on the next pass).

        Idle waits back off geometrically (5ms up to 80ms): a quiescent
        fleet must not have N forwarder threads waking every few
        milliseconds and stealing scheduler slices from query threads;
        the first batch after an idle stretch waits at most the cap,
        which forwarding (asynchronous by design) absorbs.
        """
        idle_wait = _FORWARD_POLL_S
        while not self._stop.is_set():
            moved = False
            for job, ring in self._shard_rings(shard):
                got = ring.drain(timeout=0.0)
                if got is None:
                    continue
                views, id_view, n = got
                try:
                    ok = self._handles[shard].ingest_columns(job, views, id_view)
                except (OSError, URLError):
                    ok = False
                if ok:
                    ring.commit(n)
                    _obs.counter_inc(
                        "serve.fleet_rows_forwarded", n, shard=str(shard)
                    )
                    moved = True
                else:
                    ring.commit(0)
                    _obs.counter_inc(
                        "serve.fleet_forward_errors", shard=str(shard)
                    )
            if moved:
                idle_wait = _FORWARD_POLL_S
            else:
                self._stop.wait(idle_wait)
                idle_wait = min(idle_wait * 2, _FORWARD_IDLE_MAX_S)

    def staged_rows(self) -> int:
        """Rows parked in staging rings, not yet on a worker."""
        return sum(r.depth() for r in list(self._rings.values()))

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for staging rings to drain, then flush every worker."""
        deadline = time.monotonic() + float(timeout)
        while self.staged_rows() > 0:
            if time.monotonic() >= deadline:
                return False
            time.sleep(_FORWARD_POLL_S)
        remaining = max(0.1, deadline - time.monotonic())
        results = self._scatter(
            "flush", lambda s, h: h.flush(remaining), count=False
        )
        return all(bool(ok) for ok in results.values())

    # ----------------------------------------------------------- scatter-gather
    def _scatter(
        self,
        what: str,
        fn: Callable[[int, Any], Any],
        count: bool = True,
    ) -> Dict[int, Any]:
        if count:
            _obs.counter_inc("serve.scatter_queries", op=what)
        futures = {
            s: self._pool.submit(fn, s, self._handles[s])
            for s in range(self.num_shards)
        }
        return {
            s: f.result(timeout=self.query_timeout) for s, f in futures.items()
        }

    def top_k(
        self, job: str, k: int, key: Any = None, largest: bool = True
    ) -> Tuple[List[float], List[int]]:
        """Global top-k over the union of streams: per-shard device top-k,
        merged O(k * num_shards) on the host.

        Exactness: the global top-k is a subset of the union of local
        top-ks (each shard returns min(k, span_width) candidates), and the
        merge ranks by ``(score, global_id)`` with NaN scored as the
        device kernel scores it — so the result is the single-worker
        ranking, ties and all.
        """
        k = int(k)
        total = self.router.num_streams(job)
        if not 1 <= k <= total:
            raise MetricsTPUUserError(
                f"top_k k must be in [1, {total}], got {k}"
            )
        per = self._scatter(
            "top_k",
            lambda s, h: h.top_k(
                job,
                min(k, self.router.span_width(job, s)),
                key=key,
                largest=largest,
            ),
        )
        fill = -math.inf if largest else math.inf
        candidates: List[Tuple[float, int, float]] = []
        for shard, (values, local_ids) in per.items():
            lo, _hi = self.router.span(job, shard)
            for value, local in zip(values, local_ids):
                value = float(value)
                score = fill if math.isnan(value) else value
                candidates.append((score, lo + int(local), value))
        candidates.sort(
            key=lambda c: ((-c[0] if largest else c[0]), c[1])
        )
        top = candidates[:k]
        return [v for _s, _g, v in top], [g for _s, g, _v in top]

    def where(
        self, job: str, op: str, threshold: float, k: int, key: Any = None
    ) -> Tuple[List[int], int]:
        """First-k matching global stream ids + total match count.

        Per-shard ids ascend within ascending spans, so concatenating in
        shard order IS global ascending order — same ids, same order, as
        one worker over the whole axis.
        """
        k = int(k)
        per = self._scatter(
            "where",
            lambda s, h: h.where(
                job,
                op,
                threshold,
                min(k, self.router.span_width(job, s)),
                key=key,
            ),
        )
        gids: List[int] = []
        total = 0
        for shard in sorted(per):
            local_ids, matches = per[shard]
            lo, _hi = self.router.span(job, shard)
            gids.extend(lo + int(i) for i in local_ids)
            total += int(matches)
        return gids[:k], total

    def compute(self, job: str) -> Any:
        """One job's full value: owner read (plain) or span concat
        (multistream) — the stream axis reassembles in global order."""
        if not self.router.is_multistream(job):
            owner = self.router.owner(job)
            _obs.counter_inc("serve.scatter_queries", op="compute")
            return self._handles[owner].compute(job)
        per = self._scatter("compute", lambda s, h: h.compute(job))
        return _concat_streams([per[s] for s in sorted(per)])

    def compute_streams(self, job: str, stream_ids: Sequence[int]) -> List[Any]:
        """Per-stream reads reassembled in the caller's input order."""
        total = self.router.num_streams(job)
        ids = np.asarray([int(i) for i in stream_ids], np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= total):
            raise MetricsTPUUserError(
                f"stream ids must be in [0, {total}), got "
                f"{[int(i) for i in ids if i < 0 or i >= total]}"
            )
        _obs.counter_inc("serve.scatter_queries", op="compute_streams")
        parts = self.router.partition_ids(job, ids)
        futures = {
            s: self._pool.submit(
                self._handles[s].compute_streams, job, [int(i) for i in local]
            )
            for s, (_pos, local) in parts.items()
        }
        out: List[Any] = [None] * int(ids.shape[0])
        for s, (positions, _local) in parts.items():
            values = futures[s].result(timeout=self.query_timeout)
            for position, value in zip(positions, values):
                out[int(position)] = value
        return out

    def compute_all(self) -> Dict[str, Any]:
        """Every routed job's value, shards merged (the fleet-wide answer
        the failover drill compares bitwise)."""
        return {job: self.compute(job) for job in self.router.jobs()}

    # --------------------------------------------------------------- liveness
    def health(self) -> Dict[str, Any]:
        """Per-shard probe rollup; ``status`` is ``"serving"`` only when
        every shard is."""
        futures = {
            s: self._pool.submit(self._handles[s].health)
            for s in range(self.num_shards)
        }
        shards: List[Dict[str, Any]] = []
        for s in range(self.num_shards):
            try:
                info = futures[s].result(timeout=self.query_timeout)
            except Exception as err:  # noqa: BLE001 — a dead worker is data, not a crash
                info = {"status": "unreachable", "error": str(err)}
            shards.append(dict(info, shard=s))
        dead = [s for s, info in enumerate(shards) if info.get("status") != "serving"]
        return {
            "status": "serving" if not dead else "degraded",
            "num_shards": self.num_shards,
            "dead_shards": dead,
            "staged_rows": self.staged_rows(),
            "shards": shards,
        }

    def failover(self, shard: int) -> Any:
        """Replace a dead shard's handle via the ``respawn`` callback.

        The callback restores the shard's latest checkpoint into a fresh
        worker and returns its handle; rows parked in the shard's staging
        rings then drain to the replacement automatically.
        """
        if self._respawn is None:
            raise MetricsTPUUserError(
                "failover needs a respawn callback; construct the "
                "coordinator with respawn=..."
            )
        shard = int(shard)
        if not 0 <= shard < self.num_shards:
            raise MetricsTPUUserError(
                f"shard must be in [0, {self.num_shards}), got {shard}"
            )
        replacement = self._respawn(shard)
        self._handles[shard] = replacement
        _obs.counter_inc("serve.failovers", shard=str(shard))
        return replacement


def _concat_streams(parts: List[Any]) -> Any:
    """Concatenate per-shard jsonable computes along the stream axis.

    Shards return either a list (stacked per-stream values) or a dict of
    such lists (structured computes); spans are contiguous and ascending,
    so plain concatenation in shard order reassembles global stream order.
    """
    if not parts:
        return []
    if isinstance(parts[0], dict):
        return {
            key: _concat_streams([p[key] for p in parts]) for key in parts[0]
        }
    out: List[Any] = []
    for p in parts:
        out.extend(p)
    return out


# --------------------------------------------------------------------------
# HTTP frontend
# --------------------------------------------------------------------------


class FleetHTTPServer(PooledHTTPServer):
    """Pooled HTTP server carrying the owning coordinator reference."""

    def __init__(
        self,
        address: Tuple[str, int],
        coordinator: FleetCoordinator,
        pool_threads: int = 8,
        backlog: int = 64,
    ) -> None:
        super().__init__(
            address, _FleetHandler, pool_threads=pool_threads, backlog=backlog
        )
        self.coordinator = coordinator


def make_fleet_http_server(
    host: str,
    port: int,
    coordinator: FleetCoordinator,
    pool_threads: int = 8,
    backlog: int = 64,
) -> FleetHTTPServer:
    """Bind the coordinator's HTTP frontend; ``port=0`` picks a port."""
    return FleetHTTPServer(
        (host, port), coordinator, pool_threads=pool_threads, backlog=backlog
    )


class _FleetHandler(BaseHTTPRequestHandler):
    """The coordinator's HTTP surface — same routes as a worker, answered
    by scatter-gather instead of local state.

    * ``GET /healthz`` — per-shard liveness rollup; any dead shard is a
      503 (load balancers stop routing a fleet that cannot answer for
      every span).
    * ``GET /metrics`` — the coordinator process's runtime counters
      (``serve.shard_routes``, ``serve.scatter_queries``, ...); metric
      *values* are scraped from the workers, which own the state.
    * ``GET /query`` — ``?job=`` (merged compute), ``&streams=``,
      ``&top_k=``, ``&where=`` — merged exactly as a single worker would
      answer over the union of streams.
    * ``GET /compute_all`` — every job, merged (the failover drill's
      comparison read).
    * ``POST /ingest`` — worker-compatible JSON records, partitioned into
      shard rings (columnar when rows are scalar).
    """

    server_version = "metrics-tpu-fleet/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt: str, *args: Any) -> None:
        pass

    def _send_json(self, status: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _fail(self, status: int, message: str) -> None:
        _obs.counter_inc("serve.http_errors", status=str(status))
        self._send_json(status, {"error": message})

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        url = urlparse(self.path)
        try:
            if url.path == "/healthz":
                self._healthz()
            elif url.path == "/metrics":
                self._metrics()
            elif url.path == "/query":
                self._query(parse_qs(url.query))
            elif url.path == "/compute_all":
                self._compute_all()
            else:
                self._fail(404, f"no route {url.path!r}")
        except MetricsTPUUserError as err:
            self._fail(400, str(err))
        except BrokenPipeError:
            pass
        except Exception as err:  # one bad request must not kill the pool
            self._fail(500, f"{type(err).__name__}: {err}")

    def do_POST(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        try:
            if url.path == "/ingest":
                self._ingest()
            else:
                self._fail(404, f"no route {url.path!r}")
        except MetricsTPUUserError as err:
            self._fail(400, str(err))
        except BrokenPipeError:
            pass
        except Exception as err:
            self._fail(500, f"{type(err).__name__}: {err}")

    # ------------------------------------------------------------ endpoints
    def _healthz(self) -> None:
        coord = self.server.coordinator
        _obs.counter_inc("serve.healthz_requests")
        payload = coord.health()
        self._send_json(200 if payload["status"] == "serving" else 503, payload)

    def _metrics(self) -> None:
        _obs.counter_inc("serve.scrapes")
        text = prometheus_text()
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    @staticmethod
    def _one(params: Dict[str, List[str]], name: str) -> Optional[str]:
        vals = params.get(name)
        return vals[-1] if vals else None

    def _query(self, params: Dict[str, List[str]]) -> None:
        coord = self.server.coordinator
        name = self._one(params, "job")
        if not name:
            raise MetricsTPUUserError("query needs ?job=NAME")
        if name not in coord.router.jobs():
            self._fail(404, f"unknown job {name!r}")
            return
        _obs.counter_inc("serve.queries", job=name)
        key: Any = self._one(params, "key")
        if key is not None and key.lstrip("-").isdigit():
            key = int(key)
        out: Dict[str, Any] = {"job": name}
        streams = self._one(params, "streams")
        top_k = self._one(params, "top_k")
        where = self._one(params, "where")
        if streams is not None:
            ids = [int(s) for s in streams.split(",") if s != ""]
            out["streams"] = ids
            out["values"] = coord.compute_streams(name, ids)
        elif top_k is not None:
            largest = self._one(params, "largest") != "0"
            values, ids = coord.top_k(name, int(top_k), key=key, largest=largest)
            out["top_k"] = values
            out["stream_ids"] = ids
            out["largest"] = largest
        elif where is not None:
            op, _, threshold = where.partition(":")
            k = int(self._one(params, "k") or "16")
            ids, total = coord.where(name, op, float(threshold), k=k, key=key)
            out["stream_ids"] = ids
            out["total_matches"] = total
        else:
            out["value"] = coord.compute(name)
        self._send_json(200, out)

    def _compute_all(self) -> None:
        coord = self.server.coordinator
        _obs.counter_inc("serve.queries", job="__all__")
        self._send_json(200, {"values": coord.compute_all()})

    def _ingest(self) -> None:
        coord = self.server.coordinator
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0 or length > _MAX_INGEST_BYTES:
            raise MetricsTPUUserError(
                f"ingest needs a JSON body of 1..{_MAX_INGEST_BYTES} bytes"
            )
        try:
            payload = json.loads(self.rfile.read(length).decode())
        except (ValueError, UnicodeDecodeError) as err:
            raise MetricsTPUUserError(f"ingest body is not valid JSON: {err}")
        name = payload.get("job")
        records = payload.get("records")
        if not isinstance(name, str) or not isinstance(records, list):
            raise MetricsTPUUserError(
                'ingest body must be {"job": NAME, "records": [...]}'
            )
        if name not in coord.router.jobs():
            self._fail(404, f"unknown job {name!r}")
            return
        # validate the WHOLE batch before staging any of it (same contract
        # as the worker surface: a malformed record mid-list 400s cleanly)
        parsed: List[Tuple[Tuple[Any, ...], Optional[int]]] = []
        for i, rec in enumerate(records):
            if not isinstance(rec, dict):
                raise MetricsTPUUserError(
                    f"record {i} must be a JSON object, got {type(rec).__name__}"
                )
            values = rec.get("values")
            if not isinstance(values, list) or not values:
                raise MetricsTPUUserError(f'record {i} needs "values": [...]')
            stream_id = rec.get("stream_id")
            if stream_id is not None and (
                isinstance(stream_id, bool) or not isinstance(stream_id, int)
            ):
                raise MetricsTPUUserError(
                    f'record {i} has a non-integer "stream_id": {stream_id!r}'
                )
            parsed.append((tuple(values), stream_id))
        accepted, rejected = coord.ingest_records(name, parsed)
        status = 429 if rejected and not accepted else 200
        self._send_json(status, {"accepted": accepted, "rejected": rejected})
