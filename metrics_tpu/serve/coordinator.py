"""Fleet coordinator: one frontend over N shard workers.

The coordinator owns no metric state.  It holds a
:class:`~metrics_tpu.serve.router.ShardRouter` plus one opaque **handle**
per shard and does three things:

* **Parallel ingest** — request threads partition a batch's columns by
  shard (one vectorized ``searchsorted``) and copy them into per-
  ``(shard, job)`` :class:`~metrics_tpu.serve.columnar.ColumnRing`
  staging; one forwarder thread per shard drains ring **views** and ships
  them to its worker.  The HTTP thread never waits on a worker: a full
  ring is a counted rejection (backpressure), a dead worker just leaves
  rows parked in its ring until failover replaces the handle.
* **Scatter-gather queries** — ``top_k`` / ``where`` / ``compute`` fan
  out to every shard through a thread pool with **timed** result waits
  and merge on the coordinator.  Each worker already ranked its own span
  on device (``lax.top_k``), so the merge is O(k * num_shards) host work;
  contiguous ascending spans make the merged ranking bitwise identical to
  a single worker over the union of streams (ties break lowest-global-id
  first, NaNs sort as ``-inf``/``+inf`` exactly like the device kernel).
* **Liveness + failover** — ``health()`` rolls up per-shard probes (an
  unreachable worker marks the fleet degraded; the HTTP ``/healthz``
  answers 503) and ``failover(shard)`` swaps in a replacement handle from
  the injected ``respawn`` callback (the fleet layer restores the dead
  shard's checkpoint before handing the handle back).  With a WAL
  attached, failover replays the dead shard's log past the replacement's
  checkpoint watermarks *before* installing the handle, so the recovered
  worker answers with every durably-acked row and zero client resends.
* **Durable ingest (optional)** — when constructed with per-shard
  :class:`~metrics_tpu.serve.wal.WalWriter` instances, every accepted
  batch is framed into the target shard's WAL *under the ring mutex* (so
  ring order == seq order) and the ingest ack waits for the frame's
  group-commit fsync.  Forwarders then ship whole frames tagged with
  their seqs; workers dedup on seq, which is what makes both forward
  retries and failover replay exactly-once.

Shard handles are **duck-typed** on purpose: the coordinator never
imports or constructs worker machinery, so ``tools/analyze``'s
serve-blocking and lock-order passes check this whole module with no
opt-outs — nothing on a request thread may block.  The single sanctioned
wait is the WAL durability latch (``WalTicket.wait``): a durable ack
*means* "wait for fsync", and the wait parks on an event the dedicated
writer thread sets after one group commit — the request thread never
touches the disk itself.  A handle provides::

    ingest_columns(job, cols, stream_ids=None, seqs=None) -> bool
    ingest_rows(job, rows)                     -> (accepted, rejected)
    compute(job)                               -> jsonable
    compute_streams(job, local_ids)            -> jsonable list
    top_k(job, k, key, largest)                -> (values, local_ids)
    where(job, op, threshold, k, key)          -> (local_ids, total)
    health()                                   -> dict
    flush(timeout)                             -> bool

:class:`HTTPShard` (below) speaks that protocol to a remote worker's
HTTP surface; the in-process equivalent lives in
``metrics_tpu.serve.fleet``.
"""

from __future__ import annotations

import json
import math
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)
from urllib.error import HTTPError, URLError
from urllib.parse import parse_qs, urlparse
from urllib.request import Request, urlopen

import numpy as np

from metrics_tpu.obs import core as _obs
from metrics_tpu.obs.exporters import prometheus_text
from metrics_tpu.serve.columnar import ColumnRing
from metrics_tpu.serve.httpd import _MAX_INGEST_BYTES, PooledHTTPServer
from metrics_tpu.serve.router import migration_plan
from metrics_tpu.serve.wal import replay_frames
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = [
    "HTTPShard",
    "FleetCoordinator",
    "FleetHTTPServer",
    "make_fleet_http_server",
]

_FORWARD_POLL_S = 0.005  # forwarder idle poll (timed waits only)
_FORWARD_IDLE_MAX_S = 0.08  # idle backoff cap: keeps N sleeping forwarders
# from preempting request threads every few ms on small hosts
_WAL_ACK_TIMEOUT_S = 10.0  # durable-ack bound: a wedged disk surfaces as an
# ingest error instead of a hung request thread


def _is_scalar(value: Any) -> bool:
    return isinstance(value, (int, float)) and not isinstance(value, bool)


class HTTPShard:
    """Handle over one remote shard worker's HTTP surface.

    Every method is one request/response with a bounded socket timeout;
    nothing here takes a lock, so the lock-order pass has nothing to
    order.  Ingest serializes ring views straight onto the columnar wire
    (``POST /ingest_columns``) — the worker reconstructs the columns with
    ``np.frombuffer``; no per-record objects on either side.
    """

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 10.0,
        retries: int = 2,
        retry_backoff: float = 0.05,
    ) -> None:
        self.base = f"http://{host}:{int(port)}"
        self.timeout = float(timeout)
        self.retries = max(0, int(retries))
        self.retry_backoff = float(retry_backoff)
        self.last_checkpoint_wal_marks: Optional[Dict[str, int]] = None

    # ------------------------------------------------------------- plumbing
    def _get(self, path: str) -> Dict[str, Any]:
        """GET with bounded retry on CONNECTION failures only.

        The read endpoints are idempotent, so a refused/reset connection
        (worker mid-restart or mid-resize) earns ``retries`` linear-backoff
        attempts before the error surfaces — one blip must not flip the
        fleet health rollup to degraded.  An ``HTTPError`` means the worker
        answered; replaying cannot change a 4xx/5xx, so it raises at once.
        """
        attempt = 0
        while True:
            try:
                with urlopen(self.base + path, timeout=self.timeout) as resp:
                    return json.loads(resp.read().decode())
            except HTTPError:
                raise
            except (URLError, OSError):
                if attempt >= self.retries:
                    raise
                attempt += 1
                _obs.counter_inc("serve.shard_retries")
                time.sleep(self.retry_backoff * attempt)

    def _post(
        self,
        path: str,
        body: bytes,
        content_type: str,
        idempotent: bool = False,
    ) -> Tuple[int, Dict[str, Any]]:
        """POST; connection failures retry only when ``idempotent``.

        A blind POST retry could double-apply rows (the worker may have
        committed the first attempt before the connection dropped), so by
        default connection errors surface at once.  WAL-framed ingest
        carries per-frame sequence numbers the worker dedups on, which is
        exactly the idempotency key a safe retry needs — those calls opt
        in and get the same bounded linear-backoff retry as ``_get``.
        """
        attempt = 0
        while True:
            req = Request(
                self.base + path,
                data=body,
                headers={"Content-Type": content_type},
                method="POST",
            )
            try:
                with urlopen(req, timeout=self.timeout) as resp:
                    return resp.status, json.loads(resp.read().decode())
            except HTTPError as err:
                raw = err.read()
                try:
                    payload = json.loads(raw.decode()) if raw else {}
                except ValueError:
                    payload = {}
                return err.code, payload
            except (URLError, OSError):
                if not idempotent or attempt >= self.retries:
                    raise
                attempt += 1
                _obs.counter_inc("serve.shard_retries")
                time.sleep(self.retry_backoff * attempt)

    # --------------------------------------------------------------- ingest
    def ingest_columns(
        self,
        job: str,
        cols: Sequence[np.ndarray],
        stream_ids: Optional[np.ndarray] = None,
        seqs: Optional[Sequence[Tuple[Optional[int], int]]] = None,
    ) -> bool:
        """Ship one columnar batch; ``seqs`` carries WAL frame spans.

        ``seqs=[(seq_or_None, rows), ...]`` partitions the batch into the
        WAL frames it arrived as; the worker dedups framed spans on seq,
        so a seq-tagged POST is idempotent and connection failures earn a
        retry (a duplicate delivery lands exactly once).  Untagged ships
        keep the original never-blind-retry contract.
        """
        cols = [np.ascontiguousarray(c) for c in cols]
        header: Dict[str, Any] = {
            "job": job,
            "rows": int(cols[0].shape[0]),
            "arity": len(cols),
            "dtype": cols[0].dtype.str,
            "ids": stream_ids is not None,
        }
        if seqs is not None:
            header["seqs"] = [
                [None if s is None else int(s), int(r)] for s, r in seqs
            ]
        parts = [json.dumps(header).encode(), b"\n"]
        parts.extend(c.tobytes() for c in cols)
        if stream_ids is not None:
            parts.append(np.ascontiguousarray(stream_ids, dtype="<i4").tobytes())
        status, _ = self._post(
            "/ingest_columns",
            b"".join(parts),
            "application/octet-stream",
            idempotent=seqs is not None,
        )
        return status == 200

    def ingest_rows(
        self, job: str, rows: Sequence[Tuple[Tuple[Any, ...], Optional[int]]]
    ) -> Tuple[int, int]:
        records = [
            {"values": list(values)}
            if stream_id is None
            else {"values": list(values), "stream_id": int(stream_id)}
            for values, stream_id in rows
        ]
        status, payload = self._post(
            "/ingest",
            json.dumps({"job": job, "records": records}).encode(),
            "application/json",
        )
        if status not in (200, 429):
            raise MetricsTPUUserError(
                f"shard {self.base} rejected ingest: HTTP {status} {payload}"
            )
        return int(payload.get("accepted", 0)), int(payload.get("rejected", 0))

    # ---------------------------------------------------------------- reads
    def compute(self, job: str) -> Any:
        return self._get(f"/query?job={job}")["value"]

    def compute_streams(self, job: str, local_ids: Sequence[int]) -> List[Any]:
        ids = ",".join(str(int(i)) for i in local_ids)
        return self._get(f"/query?job={job}&streams={ids}")["values"]

    def top_k(
        self, job: str, k: int, key: Any = None, largest: bool = True
    ) -> Tuple[List[float], List[int]]:
        path = f"/query?job={job}&top_k={int(k)}&largest={1 if largest else 0}"
        if key is not None:
            path += f"&key={key}"
        out = self._get(path)
        return out["top_k"], out["stream_ids"]

    def where(
        self, job: str, op: str, threshold: float, k: int, key: Any = None
    ) -> Tuple[List[int], int]:
        path = f"/query?job={job}&where={op}:{threshold!r}&k={int(k)}"
        if key is not None:
            path += f"&key={key}"
        out = self._get(path)
        return out["stream_ids"], int(out["total_matches"])

    # ------------------------------------------------------------ liveness
    def health(self) -> Dict[str, Any]:
        try:
            return self._get("/healthz")
        except HTTPError as err:  # worker answered 503 with a JSON body
            raw = err.read()
            try:
                return json.loads(raw.decode())
            except ValueError:
                return {"status": f"http_{err.code}"}

    def flush(self, timeout: float = 10.0) -> bool:
        status, payload = self._post(
            f"/flush?timeout={float(timeout)!r}", b"{}", "application/json"
        )
        return status == 200 and bool(payload.get("flushed"))

    def checkpoint(self) -> int:
        status, payload = self._post("/checkpoint", b"{}", "application/json")
        if status != 200:
            raise MetricsTPUUserError(
                f"shard {self.base} checkpoint failed: HTTP {status} {payload}"
            )
        marks = payload.get("wal_marks")
        # stash the committed watermarks so the owner (fleet layer or a
        # soak driver holding the WalWriter) can truncate covered segments
        self.last_checkpoint_wal_marks = (
            {str(j): int(s) for j, s in marks.items()}
            if isinstance(marks, dict)
            else None
        )
        return int(payload["step"])

    # ------------------------------------------------------ elastic resize
    def _post_json(self, path: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        status, out = self._post(
            path, json.dumps(payload).encode(), "application/json"
        )
        if status != 200:
            raise MetricsTPUUserError(
                f"shard {self.base} {path} failed: HTTP {status} {out}"
            )
        return out

    def migrate_out(
        self, job: str, lo: Optional[int] = None, hi: Optional[int] = None
    ) -> Dict[str, Any]:
        body: Dict[str, Any] = {"job": job}
        if lo is not None:
            body["lo"], body["hi"] = int(lo), int(hi)
        return self._post_json("/migrate_out", body)

    def migrate_in(
        self,
        job: str,
        width: Optional[int] = None,
        span_lo: int = 0,
        pieces: Sequence[Dict[str, Any]] = (),
        plain: bool = False,
    ) -> int:
        out = self._post_json(
            "/migrate_in",
            {
                "job": job,
                "width": width,
                "span_lo": int(span_lo),
                "pieces": list(pieces),
                "plain": bool(plain),
            },
        )
        return int(out.get("adopted", 0))

    def commit_migration(self, job: str) -> None:
        self._post_json("/migrate_commit", {"job": job})

    def discard_migration(self, job: str) -> None:
        self._post_json("/migrate_commit", {"job": job, "discard": True})

    def retire_job(self, job: str) -> None:
        self._post_json("/retire_job", {"job": job})


class FleetCoordinator:
    """Routes ingest to shard rings and merges scatter-gather reads.

    Args:
        router: a built :class:`~metrics_tpu.serve.router.ShardRouter`.
        handles: one shard handle per shard, index-aligned with the
            router's shard ids (see the module docstring for the duck
            protocol).
        respawn: optional ``shard -> handle`` callback used by
            :meth:`failover`; the callback owns restoring the dead
            shard's checkpoint before returning the replacement.
        ring_capacity: rows per ``(shard, job)`` staging ring.
        ingest_dtype: dtype scalar JSON records are staged at (the
            columnar hot path; float32 halves the wire for serving).
        query_timeout: per-shard bound on every scatter-gather wait.
        wal: optional ``{shard: WalWriter}`` map; shards with a writer get
            durable-ack ingest (frames fsync'd before the ack) and
            exactly-once failover replay.  Shards absent from the map keep
            queue-ack semantics.
    """

    def __init__(
        self,
        router: Any,
        handles: Sequence[Any],
        respawn: Optional[Callable[[int], Any]] = None,
        provision: Optional[Callable[[int, Any], Any]] = None,
        retire: Optional[Callable[[int], None]] = None,
        ring_capacity: int = 8192,
        ingest_dtype: Any = np.float32,
        query_timeout: float = 30.0,
        wal: Optional[Dict[int, Any]] = None,
    ) -> None:
        if len(handles) != router.num_shards:
            raise MetricsTPUUserError(
                f"router expects {router.num_shards} shard(s), "
                f"got {len(handles)} handle(s)"
            )
        self.router = router
        self._handles: List[Any] = list(handles)
        self._respawn = respawn
        self._provision = provision
        self._retire = retire
        self.ring_capacity = int(ring_capacity)
        self.ingest_dtype = np.dtype(ingest_dtype)
        self.query_timeout = float(query_timeout)
        self._wal: Dict[int, Any] = dict(wal) if wal else {}
        self._rings: Dict[Tuple[int, str], ColumnRing] = {}
        self._rings_lock = threading.Lock()
        try:  # named in the runtime lock-witness graph
            self._rings_lock.witness_name = "FleetCoordinator._rings_lock"
        except AttributeError:
            pass
        # generous cap + lazy thread creation: scatter width survives grows
        # without ever rebuilding the pool mid-flight
        self._pool = ThreadPoolExecutor(
            max_workers=max(16, 2 * len(self._handles)),
            thread_name_prefix="fleet-scatter",
        )
        self._stop = threading.Event()
        self._forwarders: Dict[int, threading.Thread] = {}
        self._started = False
        # jobs whose spans are mid-migration: forwarders park their rows
        self._held_jobs: frozenset = frozenset()
        # set <=> no resize in flight; flush() and queries gate on it
        self._resize_done = threading.Event()
        self._resize_done.set()
        self._resize_claim = threading.Lock()
        try:
            self._resize_claim.witness_name = "FleetCoordinator._resize_claim"
        except AttributeError:
            pass

    # ---------------------------------------------------------------- lifecycle
    @property
    def num_shards(self) -> int:
        return self.router.num_shards

    def handle(self, shard: int) -> Any:
        return self._handles[int(shard)]

    def start(self) -> "FleetCoordinator":
        """Spawn one forwarder thread per shard (idempotent)."""
        if self._started:
            return self
        self._started = True
        for shard in range(self.num_shards):
            self._spawn_forwarder(shard)
        return self

    def _spawn_forwarder(self, shard: int) -> None:
        t = threading.Thread(
            target=self._forward_loop,
            args=(shard,),
            name=f"fleet-forward-{shard}",
            daemon=True,
        )
        t.start()
        self._forwarders[shard] = t

    def stop(self) -> None:
        self._stop.set()
        for t in self._forwarders.values():
            t.join(timeout=5.0)
        self._forwarders = {}
        self._pool.shutdown(wait=False)

    # ------------------------------------------------------------------ ingest
    def _ring(self, shard: int, job: str, arity: int, with_ids: bool) -> ColumnRing:
        key = (int(shard), job)
        ring = self._rings.get(key)
        if ring is not None:
            return ring
        with self._rings_lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = ColumnRing(
                    arity,
                    capacity=self.ring_capacity,
                    with_ids=with_ids,
                    dtype=self.ingest_dtype,
                )
                self._rings[key] = ring
            return ring

    def _shard_rings(self, shard: int) -> List[Tuple[str, ColumnRing]]:
        # dict reads race benignly with _ring() inserts (GIL-atomic); a
        # ring missed this pass is drained on the next poll
        return [(job, r) for (s, job), r in list(self._rings.items()) if s == shard]

    def _framed_put(
        self,
        shard: int,
        ring: ColumnRing,
        job: str,
        cols: List[np.ndarray],
        ids: Optional[np.ndarray],
        tickets: Dict[int, Any],
    ) -> bool:
        """``ring.put`` that frames the batch into the shard's WAL.

        The frame callable runs *under the ring mutex, after acceptance*,
        with the dtype-converted buffers the ring actually staged — so the
        WAL records exactly the bytes that will ship, a rejected put never
        consumes a seq, and ring order always equals seq order.  Only the
        *last* ticket per shard is kept: the writer thread commits groups
        in order, so its durability implies every earlier frame's.
        """
        writer = self._wal.get(shard)
        if writer is None:
            return ring.put(cols, ids)

        def _frame(arrs: List[np.ndarray], fids: Optional[np.ndarray]) -> int:
            ticket = writer.append(job, arrs, fids)
            tickets[shard] = ticket
            return ticket.seq

        return ring.put(cols, ids, frame=_frame)

    def _await_durable(self, tickets: Dict[int, Any]) -> None:
        """Block until every staged frame's group commit lands.

        This is the durable-ack barrier: the rows are already staged (they
        WILL reach a worker), so a failed or timed-out fsync cannot be
        reported as a rejection — it surfaces as an error the client sees
        instead of a 200, and the worker-side seq dedup absorbs the
        duplicate if the client then re-sends rows that did land.
        """
        for shard, ticket in tickets.items():
            if not ticket.wait(_WAL_ACK_TIMEOUT_S):
                _obs.counter_inc("serve.wal_ack_failures", shard=str(shard))
                raise MetricsTPUUserError(
                    f"WAL group commit failed for shard {shard}: rows are "
                    "staged but not durable"
                )

    def ingest_columns(
        self,
        job: str,
        cols: Sequence[np.ndarray],
        stream_ids: Optional[np.ndarray] = None,
    ) -> Tuple[int, int]:
        """Partition one columnar batch across shard rings.

        Returns ``(accepted, rejected)`` row counts; a rejection means the
        target shard's ring was full (its worker is slow or dead) — the
        caller sees backpressure immediately instead of queueing unbounded.
        With a WAL attached, the return waits for every accepted frame's
        group-commit fsync: a ``200`` built from this count is a durable
        ack, not a queue ack.
        """
        cols = [np.asarray(c).reshape(-1) for c in cols]
        n = int(cols[0].shape[0]) if cols else 0
        if n == 0:
            return 0, 0
        tickets: Dict[int, Any] = {}
        if self.router.is_multistream(job):
            if stream_ids is None:
                raise MetricsTPUUserError(
                    f"job {job!r} is multistream; ingest needs stream_ids"
                )
            router = self.router
            parts = router.partition_ids(job, stream_ids)
            ids64 = np.asarray(stream_ids, np.int64).reshape(-1)
            accepted = rejected = 0
            for shard, (positions, _local_ids) in parts.items():
                ring = self._ring(shard, job, len(cols), with_ids=True)
                # rings stage GLOBAL stream ids: the shard key is only an
                # affinity hint, and the forwarder re-resolves each row's
                # owner at ship time — so rows parked across an elastic
                # resize drain to the post-flip owner automatically
                ok = self._framed_put(
                    shard,
                    ring,
                    job,
                    [c[positions] for c in cols],
                    ids64[positions],
                    tickets,
                )
                if ok:
                    accepted += int(positions.shape[0])
                else:
                    rejected += int(positions.shape[0])
            self._await_durable(tickets)
            return accepted, rejected
        shard = self.router.owner(job)
        ring = self._ring(shard, job, len(cols), with_ids=False)
        ok = self._framed_put(shard, ring, job, list(cols), None, tickets)
        self._await_durable(tickets)
        return (n, 0) if ok else (0, n)

    def ingest_records(
        self, job: str, records: Sequence[Tuple[Tuple[Any, ...], Optional[int]]]
    ) -> Tuple[int, int]:
        """Ingest parsed ``(values, stream_id)`` records.

        Scalar rows take the columnar hot path (one transpose into numpy,
        then :meth:`ingest_columns`); rows with array-valued fields fall
        back to per-shard record forwarding through ``handle.ingest_rows``.
        """
        if not records:
            return 0, 0
        multistream = self.router.is_multistream(job)
        scalar = all(
            all(_is_scalar(v) for v in values) for values, _sid in records
        )
        same_arity = len({len(values) for values, _sid in records}) == 1
        if multistream:
            missing = sum(1 for _v, sid in records if sid is None)
            if missing:
                _obs.counter_inc(
                    "serve.records_rejected", missing, reason="no_stream_id"
                )
                records = [r for r in records if r[1] is not None]
                if not records:
                    return 0, missing
        else:
            missing = 0
        accepted = rejected = 0
        if scalar and same_arity:
            arity = len(records[0][0])
            vals = np.asarray(
                [values for values, _sid in records], self.ingest_dtype
            )
            cols = [vals[:, i] for i in range(arity)]
            ids = (
                np.asarray([sid for _v, sid in records], np.int64)
                if multistream
                else None
            )
            accepted, rejected = self.ingest_columns(job, cols, ids)
            return accepted, rejected + missing
        # slow path: nested array values keep per-record framing.  This
        # path ships straight to a worker (no ring to park in), so a job
        # mid-migration rejects the whole batch — backpressure, never a
        # row landing on a donor whose span already moved
        if job in self._held_jobs:
            n_held = len(records)
            _obs.counter_inc(
                "serve.records_rejected", n_held, reason="migration"
            )
            return 0, n_held + missing
        by_shard: Dict[int, List[Tuple[Tuple[Any, ...], Optional[int]]]] = {}
        for values, sid in records:
            if multistream:
                shard, local = self.router.local_id(job, int(sid))
                by_shard.setdefault(shard, []).append((values, local))
            else:
                by_shard.setdefault(self.router.owner(job), []).append(
                    (values, None)
                )
        for shard, rows in by_shard.items():
            try:
                got, lost = self._handles[shard].ingest_rows(job, rows)
            except (OSError, URLError, MetricsTPUUserError):
                _obs.counter_inc(
                    "serve.fleet_forward_errors", shard=str(shard)
                )
                got, lost = 0, len(rows)
            accepted += got
            rejected += lost
        return accepted, rejected + missing

    def _forward_loop(self, shard: int) -> None:
        """Drain this shard's rings and ship views to each row's OWNER.

        Rings stage global stream ids, and ownership is re-resolved against
        the live router at ship time: the ring's shard key is only the
        affinity the rows were staged under.  Each drain ships its maximal
        single-owner *prefix* (arrival order is preserved, so parked rows
        never leapfrog) — after an epoch flip the very same parked rows
        drain to their new owner with no re-staging.

        A worker that rejects (429) or errors leaves the rows parked in
        the ring — ``commit(0)`` releases the drain without consuming, so
        the same rows retry after backoff (and survive a failover: the
        replacement handle picks them up on the next pass).  Jobs held by
        an in-flight resize are skipped whole.

        Idle waits back off geometrically (5ms up to 80ms): a quiescent
        fleet must not have N forwarder threads waking every few
        milliseconds and stealing scheduler slices from query threads;
        the first batch after an idle stretch waits at most the cap,
        which forwarding (asynchronous by design) absorbs.  Backoff time
        spent after a failed pass accumulates in
        ``serve.forwarder_backoff_secs`` (autoscaler pressure signal).
        A forwarder whose shard left the fleet (shrink) exits once its
        rings run dry.
        """
        idle_wait = _FORWARD_POLL_S
        while not self._stop.is_set():
            moved = False
            errored = False
            router = self.router
            held = self._held_jobs
            use_wal = bool(self._wal)
            for job, ring in self._shard_rings(shard):
                if job in held:
                    continue
                if use_wal:
                    got = ring.drain_frames(timeout=0.0)
                else:
                    got = ring.drain(timeout=0.0)
                if got is None:
                    continue
                if use_wal:
                    views, id_view, n, spans = got
                else:
                    views, id_view, n = got
                    spans = None
                try:
                    if id_view is not None:
                        owners = router.owner_of_ids(job, id_view)
                        # maximal single-owner prefix: argmax finds the
                        # first owner change (fixed-shape, no nonzero)
                        mixed = owners != owners[0]
                        p = int(np.argmax(mixed)) if bool(mixed.any()) else n
                        target = int(owners[0])
                    else:
                        p, target = n, router.owner(job)
                    ship_spans: Optional[List[Tuple[Optional[int], int]]]
                    demoted = 0
                    if spans is None:
                        ship_spans = None
                    elif p >= n:
                        ship_spans = list(spans)
                    else:
                        # clip the owner prefix DOWN to a frame boundary:
                        # half a frame under a seq would let a retry or a
                        # replay double-apply the other half
                        boundary = 0
                        clipped: List[Tuple[Optional[int], int]] = []
                        for seq, rows in spans:
                            if boundary + int(rows) > p:
                                break
                            clipped.append((seq, int(rows)))
                            boundary += int(rows)
                        if boundary:
                            p = boundary
                            ship_spans = clipped
                        else:
                            # the owner split lands INSIDE the front frame
                            # (a resize moved part of a framed span): ship
                            # the prefix unframed; commit() demotes the
                            # remainder — the documented resize/WAL caveat
                            ship_spans = [(None, p)]
                            demoted = p
                    if ship_spans is not None and all(
                        s is None for s, _r in ship_spans
                    ):
                        ship_spans = None  # nothing framed: plain wire
                    if id_view is not None:
                        lo = router.span(job, target)[0]
                        ship_ids = (
                            id_view[:p].astype(np.int64) - lo
                        ).astype(np.int32)
                        ship_views = [v[:p] for v in views]
                    else:
                        ship_ids = None
                        ship_views = (
                            views if p >= n else [v[:p] for v in views]
                        )
                    if ship_spans is not None:
                        ok = self._handles[target].ingest_columns(
                            job, ship_views, ship_ids, seqs=ship_spans
                        )
                    else:
                        ok = self._handles[target].ingest_columns(
                            job, ship_views, ship_ids
                        )
                    if ok and demoted:
                        _obs.counter_inc("serve.wal_unframed_rows", demoted)
                except (OSError, URLError, IndexError):
                    # IndexError: the router moved under us (shrink); the
                    # rows park and re-route against the new epoch
                    ok = False
                    p = 0
                if ok:
                    ring.commit(p)
                    _obs.counter_inc(
                        "serve.fleet_rows_forwarded", p, shard=str(shard)
                    )
                    moved = True
                else:
                    ring.commit(0)
                    errored = True
                    _obs.counter_inc(
                        "serve.fleet_forward_errors", shard=str(shard)
                    )
            if moved:
                idle_wait = _FORWARD_POLL_S
            else:
                if (
                    shard >= self.router.num_shards
                    and not any(
                        r.depth() for _j, r in self._shard_rings(shard)
                    )
                ):
                    self._forwarders.pop(shard, None)
                    return  # retired shard, rings dry: done for good
                self._stop.wait(idle_wait)
                if errored:
                    _obs.counter_inc(
                        "serve.forwarder_backoff_secs",
                        idle_wait,
                        shard=str(shard),
                    )
                idle_wait = min(idle_wait * 2, _FORWARD_IDLE_MAX_S)

    def staged_rows(self) -> int:
        """Rows parked in staging rings, not yet on a worker."""
        return sum(r.depth() for r in list(self._rings.values()))

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for staging rings to drain, then flush every worker.

        A flush that lands mid-resize WAITS for the migration (held rows
        are parked on purpose and will drain after the epoch flip) instead
        of reporting success with rows still parked; ``False`` means the
        deadline passed with rows still in flight, never that rows were
        forgotten.
        """
        deadline = time.monotonic() + float(timeout)
        while True:
            # gate on the resize first: held rings cannot drain until the
            # flip releases them, so polling depths alone would spin
            if not self._resize_done.wait(
                timeout=max(0.0, deadline - time.monotonic())
            ):
                return False
            while self.staged_rows() > 0:
                if time.monotonic() >= deadline:
                    return False
                time.sleep(_FORWARD_POLL_S)
            if self._resize_done.is_set():
                break  # drained, and no new resize started meanwhile
        remaining = max(0.1, deadline - time.monotonic())
        results = self._scatter(
            "flush", lambda s, h: h.flush(remaining), count=False
        )
        return all(bool(ok) for ok in results.values())

    # ----------------------------------------------------------- scatter-gather
    def _scatter(
        self,
        what: str,
        fn: Callable[[int, Any], Any],
        count: bool = True,
        router: Optional[Any] = None,
    ) -> Dict[int, Any]:
        if count:
            _obs.counter_inc("serve.scatter_queries", op=what)
        width = (router or self.router).num_shards
        futures = {
            s: self._pool.submit(fn, s, self._handles[s]) for s in range(width)
        }
        return {
            s: f.result(timeout=self.query_timeout) for s, f in futures.items()
        }

    def _with_router(self, fn: Callable[[Any], Any]) -> Any:
        """Run one scatter read against a router snapshot, retrying when an
        elastic resize flips the epoch mid-read.

        A read that raced the flip can see a worker answering for a span
        it no longer (or does not yet) own; retrying against the new
        snapshot makes the read linearize cleanly on one side of the flip.
        Errors unrelated to a flip surface on the first attempt.
        """
        deadline = time.monotonic() + self.query_timeout
        while True:
            router = self.router
            try:
                return fn(router)
            except Exception:
                if router is self.router and self._resize_done.is_set():
                    raise
                if time.monotonic() >= deadline:
                    raise
                time.sleep(_FORWARD_POLL_S)

    def top_k(
        self, job: str, k: int, key: Any = None, largest: bool = True
    ) -> Tuple[List[float], List[int]]:
        """Global top-k over the union of streams: per-shard device top-k,
        merged O(k * num_shards) on the host.

        Exactness: the global top-k is a subset of the union of local
        top-ks (each shard returns min(k, span_width) candidates), and the
        merge ranks by ``(score, global_id)`` with NaN scored as the
        device kernel scores it — so the result is the single-worker
        ranking, ties and all.
        """
        k = int(k)
        total = self.router.num_streams(job)
        if not 1 <= k <= total:
            raise MetricsTPUUserError(
                f"top_k k must be in [1, {total}], got {k}"
            )

        def _run(router: Any) -> Tuple[List[float], List[int]]:
            per = self._scatter(
                "top_k",
                lambda s, h: h.top_k(
                    job,
                    min(k, router.span_width(job, s)),
                    key=key,
                    largest=largest,
                ),
                router=router,
            )
            fill = -math.inf if largest else math.inf
            candidates: List[Tuple[float, int, float]] = []
            for shard, (values, local_ids) in per.items():
                lo, _hi = router.span(job, shard)
                for value, local in zip(values, local_ids):
                    value = float(value)
                    score = fill if math.isnan(value) else value
                    candidates.append((score, lo + int(local), value))
            candidates.sort(
                key=lambda c: ((-c[0] if largest else c[0]), c[1])
            )
            top = candidates[:k]
            return [v for _s, _g, v in top], [g for _s, g, _v in top]

        return self._with_router(_run)

    def where(
        self, job: str, op: str, threshold: float, k: int, key: Any = None
    ) -> Tuple[List[int], int]:
        """First-k matching global stream ids + total match count.

        Per-shard ids ascend within ascending spans, so concatenating in
        shard order IS global ascending order — same ids, same order, as
        one worker over the whole axis.
        """
        k = int(k)

        def _run(router: Any) -> Tuple[List[int], int]:
            per = self._scatter(
                "where",
                lambda s, h: h.where(
                    job,
                    op,
                    threshold,
                    min(k, router.span_width(job, s)),
                    key=key,
                ),
                router=router,
            )
            gids: List[int] = []
            total = 0
            for shard in sorted(per):
                local_ids, matches = per[shard]
                lo, _hi = router.span(job, shard)
                gids.extend(lo + int(i) for i in local_ids)
                total += int(matches)
            return gids[:k], total

        return self._with_router(_run)

    def compute(self, job: str) -> Any:
        """One job's full value: owner read (plain) or span concat
        (multistream) — the stream axis reassembles in global order."""
        if not self.router.is_multistream(job):
            _obs.counter_inc("serve.scatter_queries", op="compute")
            return self._with_router(
                lambda router: self._handles[router.owner(job)].compute(job)
            )

        def _run(router: Any) -> Any:
            per = self._scatter(
                "compute", lambda s, h: h.compute(job), router=router
            )
            total = router.num_streams(job)
            merged = _concat_streams([per[s] for s in sorted(per)])
            _require_width(merged, total)
            return merged

        return self._with_router(_run)

    def compute_streams(self, job: str, stream_ids: Sequence[int]) -> List[Any]:
        """Per-stream reads reassembled in the caller's input order."""
        total = self.router.num_streams(job)
        ids = np.asarray([int(i) for i in stream_ids], np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= total):
            raise MetricsTPUUserError(
                f"stream ids must be in [0, {total}), got "
                f"{[int(i) for i in ids if i < 0 or i >= total]}"
            )
        _obs.counter_inc("serve.scatter_queries", op="compute_streams")

        def _run(router: Any) -> List[Any]:
            parts = router.partition_ids(job, ids)
            futures = {
                s: self._pool.submit(
                    self._handles[s].compute_streams,
                    job,
                    [int(i) for i in local],
                )
                for s, (_pos, local) in parts.items()
            }
            out: List[Any] = [None] * int(ids.shape[0])
            for s, (positions, _local) in parts.items():
                values = futures[s].result(timeout=self.query_timeout)
                for position, value in zip(positions, values):
                    out[int(position)] = value
            return out

        return self._with_router(_run)

    def compute_all(self) -> Dict[str, Any]:
        """Every routed job's value, shards merged (the fleet-wide answer
        the failover drill compares bitwise)."""
        return {job: self.compute(job) for job in self.router.jobs()}

    # --------------------------------------------------------------- liveness
    def health(self) -> Dict[str, Any]:
        """Per-shard probe rollup; ``status`` is ``"serving"`` only when
        every shard is."""
        router = self.router
        width = router.num_shards
        futures = {
            s: self._pool.submit(self._handles[s].health) for s in range(width)
        }
        shards: List[Dict[str, Any]] = []
        for s in range(width):
            try:
                info = futures[s].result(timeout=self.query_timeout)
            except Exception as err:  # noqa: BLE001 — a dead worker is data, not a crash
                info = {"status": "unreachable", "error": str(err)}
            shards.append(dict(info, shard=s))
        dead = [s for s, info in enumerate(shards) if info.get("status") != "serving"]
        return {
            "status": "serving" if not dead else "degraded",
            "num_shards": width,
            "epoch": int(getattr(router, "epoch", 0)),
            "resizing": not self._resize_done.is_set(),
            "dead_shards": dead,
            "staged_rows": self.staged_rows(),
            "shards": shards,
        }

    def failover(self, shard: int) -> Any:
        """Replace a dead shard's handle via the ``respawn`` callback.

        The callback restores the shard's latest checkpoint into a fresh
        worker and returns its handle; rows parked in the shard's staging
        rings then drain to the replacement automatically.

        With a WAL attached, the shard's log is replayed into the
        replacement *before* the handle goes live: every frame past the
        restored checkpoint's applied-seq watermarks ships seq-tagged (the
        worker floor dedups any frame the checkpoint already covered), so
        the recovered worker answers queries with every durably-acked row
        — no client resend, bitwise the same state as a worker that never
        died.  Rows that drained to a *different* owner before the crash
        (possible only after a resize re-homed part of this shard's span)
        are skipped: that owner still holds them.
        """
        if self._respawn is None:
            raise MetricsTPUUserError(
                "failover needs a respawn callback; construct the "
                "coordinator with respawn=..."
            )
        shard = int(shard)
        if not 0 <= shard < self.num_shards:
            raise MetricsTPUUserError(
                f"shard must be in [0, {self.num_shards}), got {shard}"
            )
        replacement = self._respawn(shard)
        writer = self._wal.get(shard)
        if writer is not None:
            self._replay_wal(shard, writer, replacement)
        self._handles[shard] = replacement
        _obs.counter_inc("serve.failovers", shard=str(shard))
        return replacement

    def _replay_wal(self, shard: int, writer: Any, replacement: Any) -> None:
        """Push a shard's WAL past the replacement's watermarks into it."""
        try:
            info = replacement.health()
            raw = info.get("wal_marks") or {}
        except Exception:  # noqa: BLE001 — a probe failure just means replay-all
            raw = {}
        marks = {str(j): int(s) for j, s in raw.items()}
        router = self.router
        deadline = time.monotonic() + self.query_timeout
        replayed = 0
        for frame in replay_frames(
            writer.directory, watermarks=marks, on_error="skip_segment"
        ):
            if frame.stream_ids is not None:
                owners = router.owner_of_ids(
                    frame.job, frame.stream_ids.astype(np.int64)
                )
                mask = owners == shard
                if not bool(mask.any()):
                    continue  # re-homed by a resize: the new owner has them
                lo = router.span(frame.job, shard)[0]
                ship_ids = (
                    frame.stream_ids[mask].astype(np.int64) - lo
                ).astype(np.int32)
                ship_cols = [np.ascontiguousarray(c[mask]) for c in frame.cols]
            else:
                if router.owner(frame.job) != shard:
                    continue
                ship_ids = None
                ship_cols = [np.ascontiguousarray(c) for c in frame.cols]
            rows = int(ship_cols[0].shape[0])
            while True:
                ok = False
                try:
                    ok = replacement.ingest_columns(
                        frame.job, ship_cols, ship_ids, seqs=[(frame.seq, rows)]
                    )
                except (OSError, URLError):
                    ok = False
                if ok:
                    replayed += rows
                    break
                if time.monotonic() >= deadline:
                    raise MetricsTPUUserError(
                        f"WAL replay to shard {shard} replacement stalled "
                        f"at seq {frame.seq}"
                    )
                time.sleep(_FORWARD_POLL_S)  # replacement backpressure
        if replayed:
            _obs.counter_inc(
                "serve.wal_replayed_rows", replayed, shard=str(shard)
            )

    # ---------------------------------------------------------------- elastic
    def ring_stats(self) -> Dict[str, Any]:
        """Occupancy snapshot of every staging ring — the autoscaler's
        input signal (and an operator debugging read)."""
        rings = [
            {
                "shard": s,
                "job": job,
                "depth": ring.depth(),
                "pending": ring.pending(),
                "high_water": ring.high_water(),
            }
            for (s, job), ring in sorted(self._rings.items())
        ]
        return {
            "num_shards": self.router.num_shards,
            "epoch": int(getattr(self.router, "epoch", 0)),
            "ring_capacity": self.ring_capacity,
            "rings": rings,
            "staged_rows": sum(r["depth"] for r in rings),
            "held_jobs": sorted(self._held_jobs),
            "resizing": not self._resize_done.is_set(),
        }

    def resize(
        self,
        num_shards: int,
        timeout: float = 60.0,
        phase_hook: Optional[Callable[[str], None]] = None,
    ) -> Dict[str, Any]:
        """Live fleet resize: migrate every span that changes owner, then
        flip the router to a new epoch — ingest and queries keep flowing.

        The protocol (each boundary reported to ``phase_hook``):

        1. **planned** — build the target router and the minimal
           :func:`~metrics_tpu.serve.router.migration_plan` between epochs.
        2. **provisioned** — fresh workers for added shards (grow only).
        3. **held** — forwarders stop shipping the affected jobs; their
           rows park in the staging rings (whole-batch backpressure is the
           only degradation), and the one drain per ring that may already
           be in flight is waited out.
        4. **quiesced** — every old worker flushes, so queued rows are in
           the exported state.
        5. **staged** — donors export each moving span (a pure read; they
           keep serving), recipients assemble their post-resize metrics,
           STAGED — nothing is live yet.
        6. **flipped** — the handle list is extended, then the router
           reference is swapped: one atomic store is the commit point.
        7. **committed** — each affected worker swaps its staged metric
           live (a pointer swap under the job lock), then the holds lift.
        8. **released / drained** — parked rows re-resolve their owner
           against the new epoch and drain; donor shards retire plain jobs
           that moved away; shrink retires the departed workers.

        Any failure BEFORE the flip aborts cleanly: exports were reads,
        staged state is discarded, provisioned workers are retired, and
        the old epoch keeps serving — the remedy is ``failover`` of the
        failed shard and a fresh ``resize``.  Failure AFTER the flip is
        ordinary failover territory for the shard that failed; the holds
        for its jobs stay up (rows keep parking — safe, not silent) until
        the operator resolves it.
        """
        n = int(num_shards)
        if n < 1:
            raise MetricsTPUUserError(f"num_shards must be >= 1, got {n}")
        old_router = self.router
        if n > old_router.num_shards and self._provision is None:
            raise MetricsTPUUserError(
                "growing the fleet needs a provision callback; construct "
                "the coordinator with provision=..."
            )
        with self._resize_claim:
            if not self._resize_done.is_set():
                raise MetricsTPUUserError("a resize is already in flight")
            self._resize_done.clear()
        hook = phase_hook or (lambda _phase: None)
        t0 = time.monotonic()
        deadline = t0 + float(timeout)
        staged_handles: Dict[int, Any] = {}
        staged_imports: List[Tuple[int, Any, str]] = []
        retire_plain: List[Tuple[int, str]] = []
        flipped = False
        try:
            new_router = old_router.resized(n)
            plan = migration_plan(old_router, new_router)
            held = frozenset(plan.jobs())
            hook("planned")
            for shard in range(old_router.num_shards, n):
                staged_handles[shard] = self._provision(shard, new_router)
            hook("provisioned")
            self._held_jobs = held
            for (_s, job), ring in list(self._rings.items()):
                if job not in held:
                    continue
                while ring.pending():
                    if time.monotonic() >= deadline:
                        raise MetricsTPUUserError(
                            "resize timed out waiting for in-flight rows"
                        )
                    time.sleep(_FORWARD_POLL_S)
            hook("held")
            for shard in range(old_router.num_shards):
                if not self._handles[shard].flush(
                    max(0.1, deadline - time.monotonic())
                ):
                    raise MetricsTPUUserError(
                        f"shard {shard} failed to quiesce for resize"
                    )
            hook("quiesced")
            rows_moved = 0
            for job in sorted(j for j in held if old_router.is_multistream(j)):
                for recipient in range(n):
                    new_lo, new_hi = new_router.span(job, recipient)
                    if recipient < old_router.num_shards and old_router.span(
                        job, recipient
                    ) == (new_lo, new_hi):
                        continue  # span unchanged: nothing to rebuild
                    payloads: List[Dict[str, Any]] = []
                    for donor in range(old_router.num_shards):
                        old_lo, _old_hi = old_router.span(job, donor)
                        lo = max(new_lo, old_lo)
                        hi = min(new_hi, _old_hi)
                        if lo >= hi:
                            continue
                        piece = self._handles[donor].migrate_out(
                            job, lo - old_lo, hi - old_lo
                        )
                        # re-stamp donor-local row coordinates as GLOBAL:
                        # the recipient places pieces by global row
                        payloads.append(dict(piece, lo=int(lo), hi=int(hi)))
                        if donor != recipient:
                            rows_moved += hi - lo
                    handle = staged_handles.get(recipient)
                    if handle is None:
                        handle = self._handles[recipient]
                    handle.migrate_in(
                        job,
                        width=new_hi - new_lo,
                        span_lo=new_lo,
                        pieces=payloads,
                    )
                    staged_imports.append((recipient, handle, job))
            for move in plan.moves:
                if not move.plain:
                    continue
                piece = self._handles[move.donor].migrate_out(move.job)
                handle = staged_handles.get(move.recipient)
                if handle is None:
                    handle = self._handles[move.recipient]
                handle.migrate_in(move.job, pieces=[piece], plain=True)
                staged_imports.append((move.recipient, handle, move.job))
                retire_plain.append((move.donor, move.job))
            hook("staged")
            if n > old_router.num_shards:
                self._handles = list(self._handles) + [
                    staged_handles[s]
                    for s in range(old_router.num_shards, n)
                ]
            self.router = new_router  # THE commit point (atomic store)
            flipped = True
            hook("flipped")
            for _shard, handle, job in staged_imports:
                handle.commit_migration(job)
            hook("committed")
            self._held_jobs = frozenset()
            if self._started:
                for shard in range(old_router.num_shards, n):
                    self._spawn_forwarder(shard)
            hook("released")
            for donor, job in retire_plain:
                if donor < n:
                    self._handles[donor].retire_job(job)
            drained = True
            while any(
                r.depth()
                for (_s, j), r in list(self._rings.items())
                if j in held
            ):
                if time.monotonic() >= deadline:
                    drained = False  # rows are parked, not lost: keep going
                    break
                time.sleep(_FORWARD_POLL_S)
            hook("drained")
            if n < old_router.num_shards:
                if self._retire is not None:
                    for shard in range(n, old_router.num_shards):
                        self._retire(shard)
                self._handles = list(self._handles)[:n]
            _obs.counter_inc("serve.resizes")
            if rows_moved:
                _obs.counter_inc("serve.resize_rows_moved", rows_moved)
            return {
                "epoch": int(new_router.epoch),
                "old_shards": old_router.num_shards,
                "new_shards": n,
                "moves": len(plan.moves),
                "rows_moved": rows_moved,
                "jobs": sorted(held),
                "drained": drained,
                "wall_secs": round(time.monotonic() - t0, 6),
            }
        except BaseException:
            if not flipped:
                # clean abort: exports were pure reads and nothing staged
                # went live — lift the holds, drop staged state, tear down
                # provisioned workers; the old epoch keeps serving
                self._held_jobs = frozenset()
                for _shard, handle, job in staged_imports:
                    try:
                        handle.discard_migration(job)
                    except Exception:  # noqa: BLE001 — the worker may be the casualty
                        pass
                if self._retire is not None:
                    for shard in staged_handles:
                        try:
                            self._retire(shard)
                        except Exception:  # noqa: BLE001
                            pass
            _obs.counter_inc("serve.resize_failures")
            raise
        finally:
            self._resize_done.set()


def _require_width(merged: Any, total: int) -> None:
    """Raise when a merged multistream compute does not cover exactly the
    global stream axis — the signature of a read racing an epoch flip (a
    worker answered with its pre-commit span width); the caller's router
    retry then re-reads against a settled fleet."""
    if isinstance(merged, dict):
        for value in merged.values():
            _require_width(value, total)
        return
    if isinstance(merged, list) and len(merged) != total:
        raise MetricsTPUUserError(
            f"merged stream axis has {len(merged)} row(s), expected {total}"
        )


def _concat_streams(parts: List[Any]) -> Any:
    """Concatenate per-shard jsonable computes along the stream axis.

    Shards return either a list (stacked per-stream values) or a dict of
    such lists (structured computes); spans are contiguous and ascending,
    so plain concatenation in shard order reassembles global stream order.
    """
    if not parts:
        return []
    if isinstance(parts[0], dict):
        return {
            key: _concat_streams([p[key] for p in parts]) for key in parts[0]
        }
    out: List[Any] = []
    for p in parts:
        out.extend(p)
    return out


# --------------------------------------------------------------------------
# HTTP frontend
# --------------------------------------------------------------------------


class FleetHTTPServer(PooledHTTPServer):
    """Pooled HTTP server carrying the owning coordinator reference."""

    def __init__(
        self,
        address: Tuple[str, int],
        coordinator: FleetCoordinator,
        pool_threads: int = 8,
        backlog: int = 64,
    ) -> None:
        super().__init__(
            address, _FleetHandler, pool_threads=pool_threads, backlog=backlog
        )
        self.coordinator = coordinator


def make_fleet_http_server(
    host: str,
    port: int,
    coordinator: FleetCoordinator,
    pool_threads: int = 8,
    backlog: int = 64,
) -> FleetHTTPServer:
    """Bind the coordinator's HTTP frontend; ``port=0`` picks a port."""
    return FleetHTTPServer(
        (host, port), coordinator, pool_threads=pool_threads, backlog=backlog
    )


class _FleetHandler(BaseHTTPRequestHandler):
    """The coordinator's HTTP surface — same routes as a worker, answered
    by scatter-gather instead of local state.

    * ``GET /healthz`` — per-shard liveness rollup; any dead shard is a
      503 (load balancers stop routing a fleet that cannot answer for
      every span).
    * ``GET /metrics`` — the coordinator process's runtime counters
      (``serve.shard_routes``, ``serve.scatter_queries``, ...); metric
      *values* are scraped from the workers, which own the state.
    * ``GET /query`` — ``?job=`` (merged compute), ``&streams=``,
      ``&top_k=``, ``&where=`` — merged exactly as a single worker would
      answer over the union of streams.
    * ``GET /compute_all`` — every job, merged (the failover drill's
      comparison read).
    * ``POST /ingest`` — worker-compatible JSON records, partitioned into
      shard rings (columnar when rows are scalar).
    """

    server_version = "metrics-tpu-fleet/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt: str, *args: Any) -> None:
        pass

    def _send_json(self, status: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _fail(self, status: int, message: str) -> None:
        _obs.counter_inc("serve.http_errors", status=str(status))
        self._send_json(status, {"error": message})

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        url = urlparse(self.path)
        try:
            if url.path == "/healthz":
                self._healthz()
            elif url.path == "/metrics":
                self._metrics()
            elif url.path == "/query":
                self._query(parse_qs(url.query))
            elif url.path == "/compute_all":
                self._compute_all()
            else:
                self._fail(404, f"no route {url.path!r}")
        except MetricsTPUUserError as err:
            self._fail(400, str(err))
        except BrokenPipeError:
            pass
        except Exception as err:  # one bad request must not kill the pool
            self._fail(500, f"{type(err).__name__}: {err}")

    def do_POST(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        try:
            if url.path == "/ingest":
                self._ingest()
            else:
                self._fail(404, f"no route {url.path!r}")
        except MetricsTPUUserError as err:
            self._fail(400, str(err))
        except BrokenPipeError:
            pass
        except Exception as err:
            self._fail(500, f"{type(err).__name__}: {err}")

    # ------------------------------------------------------------ endpoints
    def _healthz(self) -> None:
        coord = self.server.coordinator
        _obs.counter_inc("serve.healthz_requests")
        payload = coord.health()
        self._send_json(200 if payload["status"] == "serving" else 503, payload)

    def _metrics(self) -> None:
        _obs.counter_inc("serve.scrapes")
        text = prometheus_text()
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    @staticmethod
    def _one(params: Dict[str, List[str]], name: str) -> Optional[str]:
        vals = params.get(name)
        return vals[-1] if vals else None

    def _query(self, params: Dict[str, List[str]]) -> None:
        coord = self.server.coordinator
        name = self._one(params, "job")
        if not name:
            raise MetricsTPUUserError("query needs ?job=NAME")
        if name not in coord.router.jobs():
            self._fail(404, f"unknown job {name!r}")
            return
        _obs.counter_inc("serve.queries", job=name)
        key: Any = self._one(params, "key")
        if key is not None and key.lstrip("-").isdigit():
            key = int(key)
        out: Dict[str, Any] = {"job": name}
        streams = self._one(params, "streams")
        top_k = self._one(params, "top_k")
        where = self._one(params, "where")
        if streams is not None:
            ids = [int(s) for s in streams.split(",") if s != ""]
            out["streams"] = ids
            out["values"] = coord.compute_streams(name, ids)
        elif top_k is not None:
            largest = self._one(params, "largest") != "0"
            values, ids = coord.top_k(name, int(top_k), key=key, largest=largest)
            out["top_k"] = values
            out["stream_ids"] = ids
            out["largest"] = largest
        elif where is not None:
            op, _, threshold = where.partition(":")
            k = int(self._one(params, "k") or "16")
            ids, total = coord.where(name, op, float(threshold), k=k, key=key)
            out["stream_ids"] = ids
            out["total_matches"] = total
        else:
            out["value"] = coord.compute(name)
        self._send_json(200, out)

    def _compute_all(self) -> None:
        coord = self.server.coordinator
        _obs.counter_inc("serve.queries", job="__all__")
        self._send_json(200, {"values": coord.compute_all()})

    def _ingest(self) -> None:
        coord = self.server.coordinator
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0 or length > _MAX_INGEST_BYTES:
            raise MetricsTPUUserError(
                f"ingest needs a JSON body of 1..{_MAX_INGEST_BYTES} bytes"
            )
        try:
            payload = json.loads(self.rfile.read(length).decode())
        except (ValueError, UnicodeDecodeError) as err:
            raise MetricsTPUUserError(f"ingest body is not valid JSON: {err}")
        name = payload.get("job")
        records = payload.get("records")
        if not isinstance(name, str) or not isinstance(records, list):
            raise MetricsTPUUserError(
                'ingest body must be {"job": NAME, "records": [...]}'
            )
        if name not in coord.router.jobs():
            self._fail(404, f"unknown job {name!r}")
            return
        # validate the WHOLE batch before staging any of it (same contract
        # as the worker surface: a malformed record mid-list 400s cleanly)
        parsed: List[Tuple[Tuple[Any, ...], Optional[int]]] = []
        for i, rec in enumerate(records):
            if not isinstance(rec, dict):
                raise MetricsTPUUserError(
                    f"record {i} must be a JSON object, got {type(rec).__name__}"
                )
            values = rec.get("values")
            if not isinstance(values, list) or not values:
                raise MetricsTPUUserError(f'record {i} needs "values": [...]')
            stream_id = rec.get("stream_id")
            if stream_id is not None and (
                isinstance(stream_id, bool) or not isinstance(stream_id, int)
            ):
                raise MetricsTPUUserError(
                    f'record {i} has a non-integer "stream_id": {stream_id!r}'
                )
            parsed.append((tuple(values), stream_id))
        accepted, rejected = coord.ingest_records(name, parsed)
        status = 429 if rejected and not accepted else 200
        self._send_json(status, {"accepted": accepted, "rejected": rejected})
