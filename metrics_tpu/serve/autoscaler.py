"""Autoscaler policy: turn serve-tier pressure signals into a target size.

Pure decision logic for the elastic fleet — this module OBSERVES and
RECOMMENDS, it never acts.  The caller (an operator loop, a drill, the
bench) feeds it :meth:`FleetCoordinator.ring_stats` snapshots plus a
counter snapshot, and asks :meth:`Autoscaler.recommend` for a target
shard count; actually applying it is ``coordinator.resize(target)`` (or
``LocalFleet.resize``), which this module deliberately cannot reach.

The two pressure signals, chosen because both are *leading* indicators
of the only degradation the fleet exhibits (whole-batch backpressure):

* **Staging-ring occupancy** — ``max(depth) / capacity`` across every
  per-(shard, job) ring.  Rings fill when forwarders cannot drain as
  fast as ingest stages; a full ring is the backpressure cliff.
* **Forwarder backoff** — the ``serve.forwarder_backoff_secs`` counter
  accumulates idle-wait only on *errored* drain passes, so its growth
  rate measures how much real time forwarders spend backing off from
  struggling workers.

Hysteresis gates both directions: a single hot poll (one burst filling
a ring) or one cold poll must not flap the fleet through a live
migration, so a grow or shrink is recommended only after ``hysteresis``
CONSECUTIVE agreeing observations — and never while a resize is already
in flight.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Mapping, Optional, Tuple

from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["AutoscalerConfig", "FleetSignals", "Autoscaler", "autoscale_step"]

_BACKOFF_COUNTER = "serve.forwarder_backoff_secs"


@dataclass(frozen=True)
class AutoscalerConfig:
    """Thresholds and limits for the scaling policy.

    ``high_occupancy``/``grow_backoff_secs`` are OR'd for growth (either
    pressure signal alone justifies shards); shrink needs occupancy
    below ``low_occupancy`` AND no fresh backoff — scaling down under
    any pressure is never right.
    """

    min_shards: int = 1
    max_shards: int = 16
    high_occupancy: float = 0.50  # grow at/above this ring-fill fraction
    low_occupancy: float = 0.05  # shrink candidate below this fraction
    grow_backoff_secs: float = 0.5  # new backoff per observation forcing grow
    hysteresis: int = 3  # consecutive agreeing observations required
    step: int = 1  # shards added/removed per recommendation

    def __post_init__(self) -> None:
        if not 1 <= self.min_shards <= self.max_shards:
            raise MetricsTPUUserError(
                f"need 1 <= min_shards <= max_shards, got "
                f"[{self.min_shards}, {self.max_shards}]"
            )
        if not 0.0 <= self.low_occupancy < self.high_occupancy <= 1.0:
            raise MetricsTPUUserError(
                "need 0 <= low_occupancy < high_occupancy <= 1, got "
                f"[{self.low_occupancy}, {self.high_occupancy}]"
            )
        if self.hysteresis < 1 or self.step < 1:
            raise MetricsTPUUserError("hysteresis and step must be >= 1")


@dataclass(frozen=True)
class FleetSignals:
    """One observation of fleet pressure, normalized for the policy."""

    num_shards: int
    occupancy: float  # max ring depth / ring capacity, in [0, 1]
    backoff_secs: float  # cumulative forwarder backoff (monotone counter)
    resizing: bool = False

    @classmethod
    def from_stats(
        cls,
        stats: Mapping[str, Any],
        counters: Optional[Mapping[Any, float]] = None,
    ) -> "FleetSignals":
        """Build an observation from ``coordinator.ring_stats()`` plus an
        ``obs.core.counters_snapshot()`` (keys are ``(name, labels)``
        pairs; every shard label of the backoff counter is summed)."""
        capacity = max(1, int(stats.get("ring_capacity", 1)))
        depth = max(
            (int(ring.get("depth", 0)) for ring in stats.get("rings", ())),
            default=0,
        )
        backoff = 0.0
        if counters is not None:
            for key, value in counters.items():
                name = key[0] if isinstance(key, tuple) else key
                if name == _BACKOFF_COUNTER:
                    backoff += float(value)
        return cls(
            num_shards=int(stats.get("num_shards", 1)),
            occupancy=min(1.0, depth / capacity),
            backoff_secs=backoff,
            resizing=bool(stats.get("resizing", False)),
        )


class Autoscaler:
    """Hysteresis-gated grow/shrink policy over :class:`FleetSignals`.

    Feed every poll to :meth:`observe`; :meth:`recommend` returns the
    target shard count (== current size when no change is warranted).
    The backoff counter is monotone, so pressure is its DELTA between
    consecutive observations, not its absolute value.
    """

    def __init__(self, config: Optional[AutoscalerConfig] = None) -> None:
        self.config = config or AutoscalerConfig()
        self._last: Optional[FleetSignals] = None
        self._hot = 0  # consecutive observations wanting growth
        self._cold = 0  # consecutive observations allowing shrink

    # ---------------------------------------------------------------- policy
    def observe(self, signals: FleetSignals) -> None:
        cfg = self.config
        backoff_delta = signals.backoff_secs
        if self._last is not None:
            backoff_delta = max(
                0.0, signals.backoff_secs - self._last.backoff_secs
            )
        self._last = signals
        if signals.resizing:
            # mid-migration pressure is self-inflicted (held jobs park
            # rows); it must not feed the streaks in either direction
            return
        hot = (
            signals.occupancy >= cfg.high_occupancy
            or backoff_delta >= cfg.grow_backoff_secs
        )
        cold = signals.occupancy <= cfg.low_occupancy and backoff_delta == 0.0
        self._hot = self._hot + 1 if hot else 0
        self._cold = self._cold + 1 if cold else 0

    def recommend(self) -> int:
        """Target shard count given the observation streaks; resets the
        winning streak when it fires (the resize it triggers invalidates
        every older observation)."""
        cfg = self.config
        if self._last is None:
            return cfg.min_shards
        current = self._last.num_shards
        if self._hot >= cfg.hysteresis:
            target = min(cfg.max_shards, current + cfg.step)
            if target != current:
                self._hot = 0
                self._cold = 0
                return target
        if self._cold >= cfg.hysteresis:
            target = max(cfg.min_shards, current - cfg.step)
            if target != current:
                self._hot = 0
                self._cold = 0
                return target
        return current

    def state(self) -> Dict[str, Any]:
        """Introspection for drills and the bench report."""
        return {
            "hot_streak": self._hot,
            "cold_streak": self._cold,
            "last_occupancy": (
                None if self._last is None else round(self._last.occupancy, 6)
            ),
            "last_backoff_secs": (
                None if self._last is None else round(self._last.backoff_secs, 6)
            ),
        }


def autoscale_step(
    autoscaler: Autoscaler,
    stats: Mapping[str, Any],
    counters: Optional[Mapping[Any, float]] = None,
) -> Tuple[int, FleetSignals]:
    """One observe → recommend turn; returns ``(target, signals)``.

    Convenience for operator loops::

        target, _ = autoscale_step(scaler, coord.ring_stats(), counters)
        if target != coord.num_shards:
            fleet.resize(target)
    """
    signals = FleetSignals.from_stats(stats, counters)
    autoscaler.observe(signals)
    return autoscaler.recommend(), signals
