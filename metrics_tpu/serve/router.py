"""Stream/job -> shard routing for the sharded serve fleet.

Two placement rules, one router:

* **Multistream jobs** cut their stream axis ``[0, num_streams)`` into the
  contiguous, balanced spans of
  :func:`~metrics_tpu.multistream.sharding.shard_spans`; shard ``i`` hosts
  a :class:`~metrics_tpu.multistream.MultiStreamMetric` of exactly its
  span's width and a global stream id lands at local row ``id - lo``.
  Contiguity is what makes scatter-gather exact: per-shard results
  concatenated in shard order ARE the single-worker result in global
  stream order, and a merged top-k breaks ties lowest-global-id-first just
  like ``lax.top_k`` over the unsharded axis.
* **Plain jobs** (one scalar state, nothing to split) each live wholly on
  the shard a consistent-hash ring (:class:`HashRing`, blake2b over
  virtual nodes) picks from the job name — resizing the fleet from N to
  N+1 shards moves ~1/N of the plain jobs instead of reshuffling all of
  them.

The router is **read-only after construction**: request threads route with
no lock, no I/O, and no device work — ``tools/analyze``'s serve-blocking
and lock-order passes check this module with no opt-outs.

Out-of-range stream ids stay *deliberately routable*: they clamp to the
nearest span for placement but keep their out-of-range **local** offset,
so the owning worker's device-side drop lane counts them exactly as an
unsharded worker would (``dropped_rows`` parity under scatter).
"""

from __future__ import annotations

import hashlib
from bisect import bisect_right
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.multistream.sharding import shard_spans
from metrics_tpu.obs import core as _obs
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = [
    "HashRing",
    "MigrationPlan",
    "ShardRouter",
    "SpanMove",
    "migration_plan",
]


def _ring_point(key: str) -> int:
    """64-bit ring position of a key (blake2b: stable across processes,
    unlike ``hash()`` with PYTHONHASHSEED)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring over shard indices with virtual nodes.

    Every shard projects ``vnodes`` points onto the 2^64 ring; a key owns
    the first point clockwise from its own hash.  More virtual nodes mean
    a flatter load split (stddev ~ 1/sqrt(vnodes)) at linear ring-build
    cost; lookups stay O(log(N * vnodes)).
    """

    def __init__(self, shards: Sequence[int], vnodes: int = 64) -> None:
        shards = [int(s) for s in shards]
        if not shards:
            raise MetricsTPUUserError("HashRing needs at least one shard")
        if int(vnodes) < 1:
            raise MetricsTPUUserError(f"vnodes must be >= 1, got {vnodes}")
        points: List[Tuple[int, int]] = []
        for shard in shards:
            for v in range(int(vnodes)):
                points.append((_ring_point(f"shard-{shard}#{v}"), shard))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [s for _, s in points]

    def lookup(self, key: str) -> int:
        """The shard owning ``key``."""
        i = bisect_right(self._points, _ring_point(key)) % len(self._points)
        return self._owners[i]


class ShardRouter:
    """Maps ``(job, stream_id)`` to the worker shard that owns the state.

    Args:
        num_shards: fleet width; shards are indexed ``0..num_shards-1``.
        streams_by_job: ``{job_name: num_streams | None}`` — ``None`` marks
            a plain (unsplittable) job routed by the hash ring; an int is a
            multistream job whose stream axis is span-partitioned.
        vnodes: virtual nodes per shard on the plain-job ring.
    """

    def __init__(
        self,
        num_shards: int,
        streams_by_job: Dict[str, Optional[int]],
        vnodes: int = 64,
        epoch: int = 0,
    ) -> None:
        self.num_shards = int(num_shards)
        if self.num_shards < 1:
            raise MetricsTPUUserError(
                f"num_shards must be >= 1, got {num_shards}"
            )
        # routing generation: bumped by resized(); queries and workers can
        # tell "same layout rebuilt" from "layout actually changed"
        self.epoch = int(epoch)
        self._streams_by_job = dict(streams_by_job)
        self._vnodes = int(vnodes)
        self.ring = HashRing(range(self.num_shards), vnodes=vnodes)
        self._spans: Dict[str, List[Tuple[int, int]]] = {}
        self._bounds: Dict[str, np.ndarray] = {}
        self._plain_owner: Dict[str, int] = {}
        for job, num_streams in streams_by_job.items():
            if num_streams is None:
                self._plain_owner[job] = self.ring.lookup(job)
                continue
            try:
                spans = shard_spans(int(num_streams), self.num_shards)
            except ValueError as err:
                raise MetricsTPUUserError(
                    f"job {job!r} cannot shard {self.num_shards} ways: {err}"
                ) from None
            self._spans[job] = spans
            # span boundaries [lo_0, lo_1, ..., lo_{N-1}, S]: shard of id x
            # is searchsorted(bounds, x, 'right') - 1, clipped into range
            self._bounds[job] = np.asarray(
                [lo for lo, _ in spans] + [spans[-1][1]], np.int64
            )

    # -------------------------------------------------------------- inventory
    def jobs(self) -> List[str]:
        return sorted(list(self._spans) + list(self._plain_owner))

    def is_multistream(self, job: str) -> bool:
        self._known(job)
        return job in self._spans

    def _known(self, job: str) -> None:
        if job not in self._spans and job not in self._plain_owner:
            raise MetricsTPUUserError(
                f"unroutable job {job!r}; routed: {self.jobs()}"
            )

    def num_streams(self, job: str) -> int:
        """Total (global) stream-axis width of a multistream job."""
        self._known(job)
        if job in self._plain_owner:
            raise MetricsTPUUserError(f"plain job {job!r} has no stream axis")
        return int(self._bounds[job][-1])

    def span(self, job: str, shard: int) -> Tuple[int, int]:
        """Half-open global-stream span shard ``shard`` owns for ``job``."""
        self._known(job)
        if job in self._plain_owner:
            raise MetricsTPUUserError(f"plain job {job!r} has no stream spans")
        return self._spans[job][int(shard)]

    def span_width(self, job: str, shard: int) -> int:
        lo, hi = self.span(job, shard)
        return hi - lo

    def owner(self, job: str) -> int:
        """The single shard a plain job lives on (ring placement)."""
        self._known(job)
        if job in self._spans:
            raise MetricsTPUUserError(
                f"multistream job {job!r} spans every shard; route by stream_id"
            )
        return self._plain_owner[job]

    # ---------------------------------------------------------------- routing
    def shard_for(self, job: str, stream_id: Optional[int] = None) -> int:
        """The shard one record routes to."""
        self._known(job)
        if job in self._plain_owner:
            shard = self._plain_owner[job]
        else:
            if stream_id is None:
                raise MetricsTPUUserError(
                    f"job {job!r} is multistream; routing needs a stream_id"
                )
            bounds = self._bounds[job]
            i = int(np.searchsorted(bounds, int(stream_id), side="right")) - 1
            shard = min(max(i, 0), self.num_shards - 1)
        _obs.counter_inc("serve.shard_routes", shard=str(shard))
        return shard

    def local_id(self, job: str, stream_id: int) -> Tuple[int, int]:
        """``(shard, local_row)`` of a global stream id.  Out-of-range ids
        clamp to the edge shard but keep an out-of-range local offset, so
        the worker's device drop lane sees them (accounting parity)."""
        shard = self.shard_for(job, stream_id)
        lo, _hi = self._spans[job][shard]
        return shard, int(stream_id) - lo

    def global_id(self, job: str, shard: int, local_row: int) -> int:
        """Inverse of :meth:`local_id` for in-span rows."""
        lo, _hi = self.span(job, int(shard))
        return lo + int(local_row)

    def partition_ids(
        self, job: str, stream_ids: np.ndarray
    ) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
        """Vectorized bulk route: ``{shard: (row_positions, local_ids)}``.

        ``row_positions`` index into the input order (so the caller can
        slice value columns per shard); ``local_ids`` are already span-
        relative.  One ``searchsorted`` for the whole batch — the hot
        frontend path never loops per record.
        """
        self._known(job)
        ids = np.asarray(stream_ids, np.int64).reshape(-1)
        if job in self._plain_owner:
            raise MetricsTPUUserError(
                f"plain job {job!r} does not partition by stream_id"
            )
        bounds = self._bounds[job]
        shards = np.clip(
            np.searchsorted(bounds, ids, side="right") - 1, 0, self.num_shards - 1
        )
        # group rows by shard with one stable sort: order keeps each
        # shard's rows in arrival order, and searchsorted over the sorted
        # shard column yields every shard's contiguous slice
        order = np.argsort(shards, kind="stable")
        sorted_shards = shards[order]
        starts = np.searchsorted(sorted_shards, np.arange(self.num_shards), "left")
        stops = np.searchsorted(sorted_shards, np.arange(self.num_shards), "right")
        out: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for shard in range(self.num_shards):
            lo_i, hi_i = int(starts[shard]), int(stops[shard])
            if lo_i == hi_i:
                continue
            positions = order[lo_i:hi_i]
            lo = self._spans[job][shard][0]
            out[shard] = (positions, (ids[positions] - lo).astype(np.int32))
            _obs.counter_inc(
                "serve.shard_routes", hi_i - lo_i, shard=str(shard)
            )
        return out

    def owner_of_ids(self, job: str, stream_ids: np.ndarray) -> np.ndarray:
        """Owning shard of each GLOBAL stream id, vectorized, counter-free.

        The forwarder's ship-time lookup: rows staged under one routing
        epoch must re-resolve their owner under whatever epoch is live when
        they actually ship, so parked rows drain to the post-resize owner
        automatically.  No counters here — :meth:`partition_ids` already
        billed ``serve.shard_routes`` at ingest.
        """
        self._known(job)
        if job in self._plain_owner:
            raise MetricsTPUUserError(
                f"plain job {job!r} does not partition by stream_id"
            )
        ids = np.asarray(stream_ids, np.int64).reshape(-1)
        bounds = self._bounds[job]
        return np.clip(
            np.searchsorted(bounds, ids, side="right") - 1,
            0,
            self.num_shards - 1,
        )

    # ---------------------------------------------------------------- elastic
    def resized(self, num_shards: int) -> "ShardRouter":
        """A new router for the same jobs at a different fleet width.

        Pure construction — the live router is untouched; the caller owns
        the atomic swap.  The epoch increments so both sides of a resize
        are distinguishable even when ``num_shards`` round-trips back.
        """
        return ShardRouter(
            num_shards,
            self._streams_by_job,
            vnodes=self._vnodes,
            epoch=self.epoch + 1,
        )


# ---------------------------------------------------------------------------
# Resize planning: the minimal state movement between two router epochs.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SpanMove:
    """One contiguous global-stream span changing owner.

    ``job`` is multistream; rows ``[lo, hi)`` of its stacked states move
    from shard ``donor`` (old layout) to shard ``recipient`` (new layout).
    For a plain job the whole metric moves and ``lo``/``hi`` are ``-1``.
    """

    job: str
    lo: int
    hi: int
    donor: int
    recipient: int

    @property
    def plain(self) -> bool:
        return self.lo < 0


@dataclass(frozen=True)
class MigrationPlan:
    """Everything that must move to go from ``old`` to ``new`` routing."""

    old_shards: int
    new_shards: int
    moves: Tuple[SpanMove, ...]

    def jobs(self) -> List[str]:
        return sorted({m.job for m in self.moves})

    def rows(self) -> int:
        """Total multistream rows changing owner (plain moves excluded)."""
        return sum(m.hi - m.lo for m in self.moves if not m.plain)


def migration_plan(old: ShardRouter, new: ShardRouter) -> MigrationPlan:
    """The minimal set of :class:`SpanMove` pieces between two routers.

    Multistream jobs: intersect every new-layout span with every old-layout
    span; each non-empty intersection whose owners differ is one contiguous
    piece to move (`shard_spans` keeps spans sorted, so the intersection
    sweep is linear).  Plain jobs: the consistent-hash ring only reassigns
    jobs whose owner actually changed — growing inserts the new shard's
    virtual nodes and steals ~1/N of the keyspace, everything else stays
    put.
    """
    if sorted(old.jobs()) != sorted(new.jobs()):
        raise MetricsTPUUserError(
            "migration_plan needs the same job set on both routers; "
            f"old={old.jobs()} new={new.jobs()}"
        )
    moves: List[SpanMove] = []
    for job in old.jobs():
        if old.is_multistream(job) != new.is_multistream(job):
            raise MetricsTPUUserError(
                f"job {job!r} changed multistream-ness between routers"
            )
        if not old.is_multistream(job):
            d, r = old.owner(job), new.owner(job)
            if d != r:
                moves.append(SpanMove(job, -1, -1, d, r))
            continue
        if old.num_streams(job) != new.num_streams(job):
            raise MetricsTPUUserError(
                f"job {job!r} changed stream width between routers "
                f"({old.num_streams(job)} -> {new.num_streams(job)})"
            )
        for recipient in range(new.num_shards):
            new_lo, new_hi = new.span(job, recipient)
            for donor in range(old.num_shards):
                old_lo, old_hi = old.span(job, donor)
                lo, hi = max(new_lo, old_lo), min(new_hi, old_hi)
                if lo < hi and donor != recipient:
                    moves.append(SpanMove(job, lo, hi, donor, recipient))
    moves.sort(key=lambda m: (m.job, m.lo, m.recipient))
    return MigrationPlan(
        old_shards=old.num_shards,
        new_shards=new.num_shards,
        moves=tuple(moves),
    )
