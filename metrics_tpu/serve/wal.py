"""Write-ahead log for durable ingest: segmented frames, group-commit fsync.

The fleet's ack contract before this module was *queue-ack*: a ``200`` from
``/ingest`` meant the rows reached an in-memory :class:`ColumnRing`, and a
SIGKILL between checkpoints lost every row past the checkpoint floor unless
the client re-sent it.  The WAL upgrades the ack to *durable-ack*: the
frontend appends each accepted columnar batch as one **frame** to a
per-shard append-only log and acks only after the frame's bytes are
fsync'd.  Checkpoints record per-job *applied-seq watermarks* (via
``CheckpointManager`` extra state), so failover replays exactly the frames
past the watermark — worker-side seq-dedup makes the replay (and any
forward retry) exactly-once.

Frame format (little-endian, versioned)::

    magic   b"MTWL"                      4 bytes
    length  u32    payload byte count
    payload:
        fixed   <HQIHBBH  version, seq, rows, arity, flags, dtype_len, job_len
        dtype   ascii     numpy dtype.str of the value columns (e.g. "<f4")
        job     utf-8     job name
        cols    arity contiguous column buffers, rows * itemsize each
        ids     rows * 4  int32 stream ids (present iff flags bit 0)
    crc32   u32    zlib.crc32 over the whole payload

The payload is self-describing — the same layout a future binary
``/ingest_bin`` wire protocol can reuse verbatim (ROADMAP item 4): a frame
is a columnar batch plus routing header, whether it crosses a socket or a
crash.

Durability is amortized by **group commit**: appenders enqueue encoded
frames (sequence numbers are assigned under the writer mutex, so file
order == seq order) and a single writer thread drains the queue, writes
every pending frame, and issues ONE ``fsync`` for the group.  Concurrent
producers therefore share each disk flush; a lone producer pays one fsync
per batch.  The writer thread is the single legitimately-blocking spot in
the serve tree — the ``serve-blocking`` analysis pass bans ``fsync``
elsewhere and this module opts out line-by-line, not wholesale.

Segments rotate at ``segment_bytes``; a sealed segment whose every frame
is covered by a committed checkpoint's watermarks is deleted by
:meth:`WalWriter.truncate_covered`.  Recovery semantics mirror
``CheckpointManager.on_restore_error``: a torn tail on the *last* segment
is truncated cleanly at the last valid frame (the unacked remainder was
never promised), while mid-stream corruption surfaces through
``on_error="raise" | "skip_segment"`` in :func:`replay_frames`.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import (
    Dict,
    Iterator,
    List,
    Mapping,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from metrics_tpu.obs import core as _obs
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = [
    "WalCorruption",
    "WalFrame",
    "WalTicket",
    "WalWriter",
    "inject_wal_fault",
    "list_segments",
    "read_segment_frames",
    "replay_frames",
]

_MAGIC = b"MTWL"
_FORMAT_VERSION = 1
# version, seq, rows, arity, flags, dtype_len, job_len
_FIXED = struct.Struct("<HQIHBBH")
_LEN = struct.Struct("<I")
_FLAG_IDS = 1
_MAX_PAYLOAD = 1 << 30  # sanity bound so a corrupt length cannot OOM a read

_SEGMENT_PREFIX = "seg_"
_SEGMENT_SUFFIX = ".wal"

_REPLAY_POLICIES = ("raise", "skip_segment")


class WalCorruption(Exception):
    """A frame failed to decode: bad magic, short read, or crc mismatch."""


class WalFrame(NamedTuple):
    """One decoded append: a columnar batch plus its routing header."""

    job: str
    seq: int
    cols: Tuple[np.ndarray, ...]
    stream_ids: Optional[np.ndarray]

    @property
    def rows(self) -> int:
        return int(self.cols[0].shape[0])


class WalTicket:
    """Handle for one append: the assigned seq plus a durability latch."""

    __slots__ = ("seq", "rows", "ok", "_event")

    def __init__(self, seq: int, rows: int) -> None:
        self.seq = seq
        self.rows = rows
        self.ok = False
        self._event = threading.Event()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the frame's group commit lands; True iff durable."""
        if not self._event.wait(timeout):
            return False
        return self.ok


# ---------------------------------------------------------------------------
# frame codec


def encode_frame(
    job: str,
    seq: int,
    cols: Sequence[np.ndarray],
    stream_ids: Optional[np.ndarray] = None,
) -> bytes:
    """Serialize one columnar batch as a self-delimiting WAL frame."""
    if not cols:
        raise MetricsTPUUserError("a WAL frame needs at least one column")
    arrs = [np.ascontiguousarray(c).reshape(-1) for c in cols]
    rows = int(arrs[0].shape[0])
    if any(int(a.shape[0]) != rows for a in arrs):
        raise MetricsTPUUserError("ragged batch: columns disagree on row count")
    dtype_str = arrs[0].dtype.str.encode("ascii")
    if any(a.dtype.str.encode("ascii") != dtype_str for a in arrs):
        raise MetricsTPUUserError("WAL frames require a uniform column dtype")
    job_b = job.encode("utf-8")
    if len(job_b) > 0xFFFF or len(dtype_str) > 0xFF:
        raise MetricsTPUUserError("job name or dtype string too long for frame")
    flags = 0
    ids_b = b""
    if stream_ids is not None:
        ids = np.ascontiguousarray(stream_ids, np.int32).reshape(-1)
        if int(ids.shape[0]) != rows:
            raise MetricsTPUUserError("ragged batch: stream_ids row count mismatch")
        flags |= _FLAG_IDS
        ids_b = ids.tobytes()
    parts = [
        _FIXED.pack(
            _FORMAT_VERSION,
            int(seq),
            rows,
            len(arrs),
            flags,
            len(dtype_str),
            len(job_b),
        ),
        dtype_str,
        job_b,
    ]
    parts.extend(a.tobytes() for a in arrs)
    parts.append(ids_b)
    payload = b"".join(parts)
    return b"".join(
        (_MAGIC, _LEN.pack(len(payload)), payload, _LEN.pack(zlib.crc32(payload)))
    )


def decode_frame(buf: bytes, off: int = 0) -> Tuple[WalFrame, int]:
    """Decode the frame at ``off``; returns ``(frame, next_offset)``.

    Raises :class:`WalCorruption` on bad magic, a short buffer, or a crc
    mismatch — the caller decides whether that means a torn tail (clean
    stop) or mid-stream damage (policy).
    """
    end = len(buf)
    if off + 8 > end:
        raise WalCorruption(f"short frame header at offset {off}")
    if buf[off : off + 4] != _MAGIC:
        raise WalCorruption(f"bad frame magic at offset {off}")
    (plen,) = _LEN.unpack_from(buf, off + 4)
    if plen > _MAX_PAYLOAD:
        raise WalCorruption(f"implausible payload length {plen} at offset {off}")
    body = off + 8
    if body + plen + 4 > end:
        raise WalCorruption(f"torn frame at offset {off}")
    payload = buf[body : body + plen]
    (crc,) = _LEN.unpack_from(buf, body + plen)
    if zlib.crc32(payload) != crc:
        raise WalCorruption(f"crc mismatch at offset {off}")
    if plen < _FIXED.size:
        raise WalCorruption(f"payload shorter than fixed header at offset {off}")
    version, seq, rows, arity, flags, dtype_len, job_len = _FIXED.unpack_from(
        payload, 0
    )
    if version != _FORMAT_VERSION:
        raise WalCorruption(f"unsupported frame version {version} at offset {off}")
    p = _FIXED.size
    dtype_str = payload[p : p + dtype_len].decode("ascii")
    p += dtype_len
    job = payload[p : p + job_len].decode("utf-8")
    p += job_len
    dtype = np.dtype(dtype_str)
    col_bytes = rows * dtype.itemsize
    need = p + arity * col_bytes + (rows * 4 if flags & _FLAG_IDS else 0)
    if need != plen:
        raise WalCorruption(f"frame body size mismatch at offset {off}")
    cols = []
    for _ in range(arity):
        cols.append(np.frombuffer(payload, dtype, count=rows, offset=p))
        p += col_bytes
    ids = None
    if flags & _FLAG_IDS:
        ids = np.frombuffer(payload, np.int32, count=rows, offset=p)
    return WalFrame(job, int(seq), tuple(cols), ids), body + plen + 4


# ---------------------------------------------------------------------------
# segment reading


def list_segments(directory: str) -> List[str]:
    """Segment paths in seq order (file names sort by first seq)."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    return [
        os.path.join(directory, n)
        for n in sorted(names)
        if n.startswith(_SEGMENT_PREFIX) and n.endswith(_SEGMENT_SUFFIX)
    ]


def read_segment_frames(path: str) -> Iterator[WalFrame]:
    """Yield every frame in one segment; raises WalCorruption where it stops."""
    with open(path, "rb") as fh:
        data = fh.read()
    off = 0
    end = len(data)
    while off < end:
        frame, off = decode_frame(data, off)
        yield frame


def _scan_segment(path: str) -> Tuple[List[WalFrame], int, bool]:
    """Read a segment tolerantly: ``(frames, valid_byte_length, clean)``."""
    with open(path, "rb") as fh:
        data = fh.read()
    frames: List[WalFrame] = []
    off = 0
    end = len(data)
    while off < end:
        try:
            frame, nxt = decode_frame(data, off)
        except WalCorruption:
            return frames, off, False
        frames.append(frame)
        off = nxt
    return frames, off, True


def replay_frames(
    directory: str,
    watermarks: Optional[Mapping[str, int]] = None,
    on_error: str = "raise",
) -> Iterator[WalFrame]:
    """Yield frames past the per-job watermarks, oldest first.

    A decode failure on the **last** segment is treated as a torn tail —
    the remainder was never group-committed, so replay simply stops there.
    Damage anywhere else follows ``on_error`` (mirroring the checkpoint
    restore policies): ``"raise"`` surfaces :class:`WalCorruption`;
    ``"skip_segment"`` abandons the damaged segment wholesale — frames
    decoded before the damage are *not* yielded, since a partially-applied
    segment would break the contiguous-seq dedup contract — counts the
    loss in ``serve.wal_replay_skipped_segments`` /
    ``serve.wal_replay_skipped_rows``, and continues with the next
    segment.
    """
    if on_error not in _REPLAY_POLICIES:
        raise MetricsTPUUserError(
            f"on_error must be one of {_REPLAY_POLICIES}, got {on_error!r}"
        )
    marks = dict(watermarks or {})
    segments = list_segments(directory)
    for idx, path in enumerate(segments):
        last = idx == len(segments) - 1
        frames, _valid, clean = _scan_segment(path)
        if not clean and not last:
            if on_error == "raise":
                raise WalCorruption(
                    f"corrupt frame mid-stream in sealed segment {path}"
                )
            _obs.counter_inc("serve.wal_replay_skipped_segments")
            # the unreadable remainder is counted as a segment-granular loss;
            # rows we cannot decode cannot be counted row-exactly, so the
            # row counter carries what was salvaged alongside the skip
            _obs.counter_inc(
                "serve.wal_replay_skipped_rows", sum(f.rows for f in frames)
            )
            continue
        for frame in frames:
            if frame.seq > marks.get(frame.job, -1):
                yield frame


# ---------------------------------------------------------------------------
# writer


class WalWriter:
    """Per-shard segmented WAL with a dedicated group-commit writer thread.

    ``append`` assigns the next sequence number, encodes the frame under
    the writer mutex (so file order is seq order), and returns a
    :class:`WalTicket`; the caller acks its client only after
    ``ticket.wait()`` confirms the group commit.  All file I/O — writes,
    rotation, fsync — happens on the single writer thread, which batches
    every append queued since the previous flush into one fsync.

    ``fsync=False`` keeps the write+flush pipeline (bytes reach the OS,
    surviving SIGKILL) but skips the disk barrier — the bench sweep uses it
    to price durability, and kill-storm drills are valid either way because
    the page cache outlives the process.
    """

    def __init__(
        self,
        directory: str,
        segment_bytes: int = 4 << 20,
        fsync: bool = True,
    ) -> None:
        if int(segment_bytes) < 1:
            raise MetricsTPUUserError(
                f"segment_bytes must be >= 1, got {segment_bytes}"
            )
        self.directory = directory
        self.segment_bytes = int(segment_bytes)
        self.fsync = bool(fsync)
        os.makedirs(directory, exist_ok=True)
        self._cond = threading.Condition(threading.Lock())
        try:  # named in the runtime lock-witness graph
            self._cond._lock.witness_name = "WalWriter._cond"  # type: ignore[attr-defined]
        except AttributeError:
            pass
        self._pending: List[Tuple[bytes, WalTicket]] = []
        self._stop = False
        self._closed = False
        self._fh = None
        self._active_path: Optional[str] = None
        self._active_size = 0
        self._segment_rows: Dict[str, int] = {}
        self._lag_rows = 0
        self._lag_hwm = 0
        self._next_seq = self._recover()
        self._thread = threading.Thread(
            target=self._run, name=f"wal-writer:{os.path.basename(directory)}",
            daemon=True,
        )
        self._thread.start()

    # -------------------------------------------------------------- recovery
    def _recover(self) -> int:
        """Rebuild next_seq + lag from disk; truncate a torn last-segment tail."""
        next_seq = 0
        segments = list_segments(self.directory)
        for idx, path in enumerate(segments):
            frames, valid, clean = _scan_segment(path)
            if frames:
                next_seq = max(next_seq, frames[-1].seq + 1)
            self._segment_rows[path] = sum(f.rows for f in frames)
            self._lag_rows += self._segment_rows[path]
            if not clean and idx == len(segments) - 1:
                # torn tail: the remainder never group-committed, so no ack
                # covers it — truncate back to the last valid frame
                with open(path, "r+b") as fh:
                    fh.truncate(valid)
                _obs.counter_inc("serve.wal_torn_tails")
            if idx == len(segments) - 1:
                self._active_path = path
                self._active_size = valid
        self._lag_hwm = self._lag_rows
        return next_seq

    # --------------------------------------------------------------- appends
    @property
    def next_seq(self) -> int:
        with self._cond:
            return self._next_seq

    def append(
        self,
        job: str,
        cols: Sequence[np.ndarray],
        stream_ids: Optional[np.ndarray] = None,
    ) -> WalTicket:
        """Queue one frame for the next group commit; returns its ticket.

        Seq assignment, encoding, and enqueue share one critical section so
        the on-disk frame order always equals the assignment order — the
        replay stream must match the ring's ship order bit for bit.
        """
        with self._cond:
            if self._closed:
                raise MetricsTPUUserError("append on a closed WalWriter")
            seq = self._next_seq
            self._next_seq += 1
            encoded = encode_frame(job, seq, cols, stream_ids)
            ticket = WalTicket(seq, int(np.asarray(cols[0]).reshape(-1).shape[0]))
            self._pending.append((encoded, ticket))
            self._cond.notify_all()
        return ticket

    def append_wait(
        self,
        job: str,
        cols: Sequence[np.ndarray],
        stream_ids: Optional[np.ndarray] = None,
        timeout: Optional[float] = 30.0,
    ) -> WalTicket:
        """``append`` + block for durability; convenience for tests/tools."""
        ticket = self.append(job, cols, stream_ids)
        ticket.wait(timeout)
        return ticket

    # --------------------------------------------------------- writer thread
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait(0.05)
                batch = self._pending
                self._pending = []
                stopping = self._stop
            if batch:
                self._write_group(batch)
                continue
            if stopping:
                return

    def _write_group(self, batch: List[Tuple[bytes, WalTicket]]) -> None:
        rows = sum(t.rows for _, t in batch)
        try:
            for encoded, ticket in batch:
                if self._fh is None or self._active_size >= self.segment_bytes:
                    self._rotate(ticket.seq)
                self._fh.write(encoded)
                self._active_size += len(encoded)
                self._segment_rows[self._active_path] = (
                    self._segment_rows.get(self._active_path, 0) + ticket.rows
                )
            self._fh.flush()
            if self.fsync:
                # the one sanctioned blocking disk barrier in the serve tree:
                # this thread exists so request paths never wait on it directly
                os.fsync(self._fh.fileno())  # analyze: ignore[serve-blocking] -- dedicated WAL writer thread; group commit IS the durability barrier
        except OSError:
            _obs.counter_inc("serve.wal_append_errors", len(batch))
            for _, ticket in batch:
                ticket.ok = False
                ticket._event.set()
            return
        _obs.counter_inc("serve.wal_appends", len(batch))
        _obs.counter_inc("serve.wal_fsyncs")
        _obs.counter_inc("serve.wal_group_commit_rows", rows)
        with self._cond:
            self._lag_rows += rows
            if self._lag_rows > self._lag_hwm:
                # delta counter, ring_occupancy_hwm style: the summed counter
                # IS the high-water mark of durable-but-untruncated rows
                _obs.counter_inc(
                    "serve.wal_lag_rows", self._lag_rows - self._lag_hwm
                )
                self._lag_hwm = self._lag_rows
        for _, ticket in batch:
            ticket.ok = True
            ticket._event.set()

    def _rotate(self, first_seq: int) -> None:
        if self._fh is not None:
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())  # analyze: ignore[serve-blocking] -- writer thread sealing a segment before rotation
            self._fh.close()
        name = f"{_SEGMENT_PREFIX}{first_seq:016d}{_SEGMENT_SUFFIX}"
        self._active_path = os.path.join(self.directory, name)
        self._fh = open(self._active_path, "ab")
        self._active_size = self._fh.tell()
        if self.fsync:
            dir_fd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dir_fd)  # analyze: ignore[serve-blocking] -- writer thread making the new segment's dirent durable
            finally:
                os.close(dir_fd)

    # ------------------------------------------------------------ truncation
    def truncate_covered(self, watermarks: Mapping[str, int]) -> int:
        """Delete sealed segments whose every frame the watermarks cover.

        ``watermarks`` is the per-job applied-seq map a *committed*
        checkpoint recorded: a frame with ``seq <= watermarks[job]`` is
        already inside the checkpoint, so replay will never need it again.
        The active segment is never deleted (the writer owns its handle).
        Returns the number of segments removed.
        """
        with self._cond:
            active = self._active_path
        removed = 0
        for path in list_segments(self.directory):
            if path == active:
                continue
            frames, _valid, clean = _scan_segment(path)
            if not clean:
                continue  # replay policy owns damaged segments, not GC
            if not frames:
                continue
            if all(f.seq <= watermarks.get(f.job, -1) for f in frames):
                rows = sum(f.rows for f in frames)
                os.remove(path)
                removed += 1
                _obs.counter_inc("serve.wal_truncated_segments")
                with self._cond:
                    self._segment_rows.pop(path, None)
                    self._lag_rows -= rows
        return removed

    def segments(self) -> List[str]:
        return list_segments(self.directory)

    def lag_rows(self) -> int:
        """Durable rows no committed checkpoint's truncation has reclaimed."""
        with self._cond:
            return self._lag_rows

    # ----------------------------------------------------------------- close
    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=10.0)
        if self._fh is not None:
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())  # analyze: ignore[serve-blocking] -- final barrier on close, writer thread already joined
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "WalWriter":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


# ---------------------------------------------------------------------------
# fault injection (test harness, ChaosStore's sibling)


def inject_wal_fault(path: str, kind: str) -> Dict[str, int]:
    """Damage one segment file in a pinned, deterministic way.

    Kinds (each maps to one recovery policy the tests assert):

    * ``"torn_tail"``  — drop the final 5 bytes, leaving a half-written
      last frame: recovery truncates at the last valid frame boundary.
    * ``"truncate"``   — cut the file mid-way through its *second* frame
      (or mid-first when only one exists): mid-stream damage, policy
      ``raise`` / ``skip_segment`` decides.
    * ``"bit_flip"``   — flip one bit inside the first frame's payload so
      its crc32 no longer matches.

    Returns ``{"offset": ..., "size": ...}`` describing the damage.
    """
    with open(path, "rb") as fh:
        data = fh.read()
    if not data:
        raise MetricsTPUUserError(f"cannot inject into empty segment {path}")
    if kind == "torn_tail":
        cut = max(len(data) - 5, 1)
        with open(path, "r+b") as fh:
            fh.truncate(cut)
        return {"offset": cut, "size": len(data) - cut}
    if kind == "truncate":
        try:
            _, second = decode_frame(data, 0)
        except WalCorruption:
            second = 0
        cut = second + 9 if second + 9 < len(data) else max(len(data) // 2, 1)
        with open(path, "r+b") as fh:
            fh.truncate(cut)
        return {"offset": cut, "size": len(data) - cut}
    if kind == "bit_flip":
        off = 8 + _FIXED.size + 1  # inside the first frame's payload
        if off >= len(data):
            off = len(data) // 2
        flipped = bytes([data[off] ^ 0x40])
        with open(path, "r+b") as fh:
            fh.seek(off)
            fh.write(flipped)
        return {"offset": off, "size": 1}
    raise MetricsTPUUserError(
        f"unknown WAL fault kind {kind!r} (torn_tail|truncate|bit_flip)"
    )
