"""Reusable load generation for the serve tier: threads or processes.

The soak harness had a one-off traffic pusher; this module promotes it
into the load generator the fleet bench and the chaos drills share:

* :func:`run_load` — **thread mode**: N in-process producer threads push
  deterministic columnar batches through any ``ingest(lo, hi) ->
  (accepted, rejected)`` callable (a worker's ``submit_columns``, a
  coordinator's ``ingest_columns``, an :class:`HTTPShard` forward — the
  callable decides), while an optional query thread samples read latency
  the whole time.  Returns a :class:`LoadReport` with records/s and query
  p50/p99.
* :func:`run_process_load` — **process mode**: spawns stdlib-only child
  processes (``_loadgen_child.py``, executed by path so children never
  pay the package import) that POST JSON ``/ingest`` batches at a real
  HTTP endpoint; reports aggregate across children.
* :class:`ColumnTraffic` — counter-keyed batch synthesis (one Philox
  stream per ``(seed, lo)``): batch ``[lo, hi)`` is the same bytes in
  every process, so drills can split ranges across producers and still
  reason about exactly which rows landed.
"""
# analyze: skip-file[serve-blocking] -- load-generation driver: it runs in
# the operator/bench process and deliberately blocks on the service under
# test (joins, HTTP round-trips), like the soak harness it grew out of.

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from metrics_tpu.obs import core as _obs
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["ColumnTraffic", "LoadReport", "run_load", "run_process_load"]

_CHILD_PATH = os.path.join(os.path.dirname(__file__), "_loadgen_child.py")


class ColumnTraffic:
    """Deterministic columnar batches: ``batch(lo, hi)`` is a pure
    function of ``(seed, lo, hi)`` — counter-keyed like
    :class:`~metrics_tpu.serve.traffic.TrafficGenerator`, but vectorized
    (one Philox draw per batch, not per record)."""

    def __init__(
        self,
        job: str,
        arity: int = 2,
        num_streams: Optional[int] = None,
        seed: int = 0,
        dyadic: bool = False,
    ) -> None:
        self.job = job
        self.arity = int(arity)
        self.num_streams = num_streams
        self.seed = int(seed)
        # dyadic quantization (multiples of 1/8) makes float32 accumulation
        # exact no matter how rows are grouped into blocks — required by
        # drills that compare fleets with DIFFERENT shardings bitwise
        # (same-sharding twins match without it: their block boundaries,
        # and so their partial-sum trees, are identical anyway)
        self.dyadic = bool(dyadic)

    def batch(
        self, lo: int, hi: int
    ) -> Tuple[List[np.ndarray], Optional[np.ndarray]]:
        n = int(hi) - int(lo)
        if n <= 0:
            raise MetricsTPUUserError(f"empty batch [{lo}, {hi})")
        rng = np.random.Generator(
            np.random.Philox(key=self.seed, counter=[0, 0, 0, int(lo)])
        )
        cols = [
            rng.random(n, dtype=np.float32) for _ in range(self.arity)
        ]
        if self.dyadic:
            cols = [np.floor(c * 8.0).astype(np.float32) / 8.0 for c in cols]
        ids = None
        if self.num_streams is not None:
            ids = rng.integers(0, self.num_streams, n).astype(np.int32)
        return cols, ids


@dataclass
class LoadReport:
    """What a load run measured (ingest throughput + read latency)."""

    records: int = 0
    accepted: int = 0
    rejected: int = 0
    elapsed_s: float = 0.0
    query_count: int = 0
    query_p50_ms: float = 0.0
    query_p99_ms: float = 0.0
    query_errors: int = 0
    errors: List[str] = field(default_factory=list)

    @property
    def records_per_s(self) -> float:
        return self.records / self.elapsed_s if self.elapsed_s > 0 else 0.0

    def merge(self, other: "LoadReport") -> "LoadReport":
        """Aggregate a child's report into this one (wall time = max:
        children run concurrently)."""
        self.records += other.records
        self.accepted += other.accepted
        self.rejected += other.rejected
        self.elapsed_s = max(self.elapsed_s, other.elapsed_s)
        self.errors.extend(other.errors)
        return self


def _percentile_ms(latencies_s: List[float], q: float) -> float:
    if not latencies_s:
        return 0.0
    return float(np.percentile(np.asarray(latencies_s), q) * 1e3)


def run_load(
    ingest: Callable[[int, int], Tuple[int, int]],
    total_records: int,
    batch_rows: int = 256,
    threads: int = 1,
    query: Optional[Callable[[], Any]] = None,
    query_interval: float = 0.002,
    flush: Optional[Callable[[], bool]] = None,
) -> LoadReport:
    """Thread-mode load: push ``total_records`` through ``ingest`` and
    (optionally) sample ``query`` latency until ingest completes.

    ``ingest(lo, hi)`` owns batch synthesis and delivery (pair it with a
    :class:`ColumnTraffic`); producer ``threads`` split the record range
    into interleaved batch slots.  ``flush`` (when given) runs inside the
    timed window — throughput then measures records *applied to state*,
    not records parked in queues.
    """
    total = int(total_records)
    if total <= 0:
        raise MetricsTPUUserError(f"total_records must be > 0, got {total}")
    batch_rows = max(1, int(batch_rows))
    n_batches = (total + batch_rows - 1) // batch_rows
    report = LoadReport()
    report_lock = threading.Lock()

    def produce(worker: int) -> None:
        accepted = rejected = sent = 0
        for b in range(worker, n_batches, max(1, int(threads))):
            lo = b * batch_rows
            hi = min(lo + batch_rows, total)
            try:
                got, lost = ingest(lo, hi)
            except Exception as err:  # noqa: BLE001 — a failed batch is data
                with report_lock:
                    report.errors.append(f"ingest[{lo}:{hi}): {err}")
                continue
            sent += hi - lo
            accepted += int(got)
            rejected += int(lost)
        with report_lock:
            report.records += sent
            report.accepted += accepted
            report.rejected += rejected

    producers = [
        threading.Thread(target=produce, args=(w,), name=f"loadgen-{w}")
        for w in range(max(1, int(threads)))
    ]
    latencies: List[float] = []
    q_errors = [0]
    done = threading.Event()

    def query_loop() -> None:
        while not done.is_set():
            t0 = time.monotonic()
            try:
                query()
                latencies.append(time.monotonic() - t0)
            except Exception:  # noqa: BLE001 — latency sampling must not die
                q_errors[0] += 1
            done.wait(query_interval)

    sampler = (
        threading.Thread(target=query_loop, name="loadgen-query")
        if query is not None
        else None
    )
    t0 = time.monotonic()
    for t in producers:
        t.start()
    if sampler is not None:
        sampler.start()
    for t in producers:
        t.join()
    if flush is not None and not flush():
        report.errors.append("flush timed out")
    report.elapsed_s = time.monotonic() - t0
    done.set()
    if sampler is not None:
        sampler.join(timeout=5.0)
    report.query_count = len(latencies)
    report.query_errors = q_errors[0]
    report.query_p50_ms = _percentile_ms(latencies, 50.0)
    report.query_p99_ms = _percentile_ms(latencies, 99.0)
    _obs.counter_inc("serve.loadgen_runs")
    return report


def run_process_load(
    url: str,
    job: str,
    total_records: int,
    processes: int = 2,
    batch_rows: int = 256,
    arity: int = 2,
    num_streams: Optional[int] = None,
    seed: int = 0,
    timeout: float = 120.0,
) -> LoadReport:
    """Process-mode load: stdlib-only children POST ``/ingest`` at ``url``.

    Children are real processes (their GILs don't share ours), launched by
    file path so none of them pays the package import.  The record range
    splits contiguously across children; each prints a JSON report line
    this parent aggregates.
    """
    total = int(total_records)
    processes = max(1, int(processes))
    per = (total + processes - 1) // processes
    procs: List[subprocess.Popen] = []
    for w in range(processes):
        lo, hi = w * per, min((w + 1) * per, total)
        if lo >= hi:
            break
        cmd = [
            sys.executable,
            _CHILD_PATH,
            "--url", url,
            "--job", job,
            "--lo", str(lo),
            "--hi", str(hi),
            "--batch-rows", str(int(batch_rows)),
            "--arity", str(int(arity)),
            "--num-streams", str(int(num_streams or 0)),
            "--seed", str(int(seed)),
        ]
        procs.append(
            subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE
            )
        )
    report = LoadReport()
    for proc in procs:
        try:
            out, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            report.errors.append("loadgen child timed out")
            continue
        if proc.returncode != 0:
            report.errors.append(
                f"loadgen child rc={proc.returncode}: {err.decode()[-200:]}"
            )
            continue
        child = json.loads(out.decode().strip().splitlines()[-1])
        report.merge(
            LoadReport(
                records=int(child["sent"]),
                accepted=int(child["accepted"]),
                rejected=int(child["rejected"]),
                elapsed_s=float(child["elapsed_s"]),
                errors=(
                    [f"child http errors: {child['errors']}"]
                    if child.get("errors")
                    else []
                ),
            )
        )
    _obs.counter_inc("serve.loadgen_runs")
    return report
