"""Ingestion pipeline: bounded record queue -> fixed-shape compiled blocks.

Producers call :meth:`IngestQueue.put` with one :class:`Record` per input
row; the single consumer thread (driven by the server) drains the queue and
micro-batches rows per job into **static-shape dispatches** — the same
discipline ``tools/shape_lint.py`` enforces on the streaming/multistream
state math, applied to the serving path:

* **Multistream jobs** fill fixed-capacity padded blocks: rows stack into a
  ``(block_rows, ...)`` block, short blocks pad with zero rows whose
  ``stream_id`` is ``-1`` and a ``num_valid`` row count — pad rows neither
  route on device nor count into the metric's ``dropped_rows`` signal, so
  padding provably never touches metric state *or* its drop accounting.
  Every block is ONE compiled ``update`` with ONE shape (``num_valid``
  rides along as a traced scalar), so the jitted program never retraces no
  matter how traffic arrives.
* **Plain jobs** (no stream routing, so no drop lane to pad into) decompose
  each flush into power-of-two chunks capped at ``block_rows``: at most
  ``log2(block_rows)+1`` distinct shapes ever reach the compiler, and every
  row is dispatched exactly once — bit-identical to calling ``update``
  directly with the same rows.
* **Residual-row carry**: a non-forced flush dispatches only whole blocks
  and carries the sub-block tail to the next flush, so steady-state
  traffic never pays the pow2 tail dispatches; the consumer forces a tail
  out only once it has waited a full ``flush_interval``.

Rows arrive either as one :class:`Record` per queue item or as a
:class:`ColumnBatch` — pre-stacked column arrays the sharded frontend
forwards as views, one queue slot per batch.

Back-pressure is the queue bound: a full queue rejects the record (counted
in ``serve.records_rejected``) instead of stalling the producer or growing
without limit.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np

from metrics_tpu.obs import core as _obs
from metrics_tpu.serve.registry import EvalJob, MetricRegistry
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["Record", "ColumnBatch", "IngestQueue", "BlockBatcher", "IngestConsumer"]


class Record(NamedTuple):
    """One input row for one job.

    ``values`` are the job metric's positional update arguments for a single
    row (scalars or fixed-shape per-row arrays — every record of a job must
    agree on shapes, the static-shape contract).  ``stream_id`` routes the
    row on multistream jobs and must be ``None`` on plain jobs.
    """

    job: str
    values: Tuple[Any, ...]
    stream_id: Optional[int] = None


class ColumnBatch(NamedTuple):
    """Many rows for one job, already columnar.

    The sharded frontend stages ingest into pre-allocated column arrays and
    forwards contiguous views — one queue item per batch instead of one
    Python object per record.  ``cols`` holds one ``(n, ...)`` array per
    update argument; ``stream_ids`` is an ``(n,)`` int32 array on
    multistream jobs and ``None`` on plain jobs.

    ``seq`` is the batch's WAL frame sequence number when durable ingest
    is on (``metrics_tpu.serve.wal``): the consumer advances its per-job
    applied-seq watermark after folding the batch, and checkpoints persist
    those watermarks so failover replays exactly the frames past them.
    """

    job: str
    cols: Tuple[np.ndarray, ...]
    stream_ids: Optional[np.ndarray] = None
    seq: Optional[int] = None


class _FlushToken:
    """Sentinel a producer enqueues to observe a drain point: the consumer
    flushes every batcher, then sets the event.

    A **hold** token additionally freezes the consumer at the drain point:
    after flushing, the consumer snapshots its WAL watermarks into
    ``marks``, signals ``done``, and then waits (bounded) on ``release``
    before applying anything else.  Checkpoints use this so the saved
    watermarks are *exactly* the state the snapshot contains — without the
    hold, a frame applied between flush and encode would be inside the
    snapshot but past the recorded watermark, and replay would double-apply
    it.  Plain (non-hold) tokens behave exactly as before.
    """

    _HOLD_TIMEOUT = 60.0  # release is belt-and-braces bounded: a crashed
    # checkpointer must not wedge the consumer forever

    def __init__(self, hold: bool = False) -> None:
        self.done = threading.Event()
        self.hold = bool(hold)
        self.release = threading.Event()
        self.marks: Dict[str, int] = {}


class IngestQueue:
    """Bounded MPSC record queue with rejection accounting."""

    def __init__(self, capacity: int = 4096) -> None:
        if int(capacity) < 1:
            raise MetricsTPUUserError(f"queue capacity must be >= 1, got {capacity}")
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=int(capacity))
        self.capacity = int(capacity)

    def put(
        self, record: Union[Record, ColumnBatch], timeout: Optional[float] = None
    ) -> bool:
        """Enqueue one record (or one columnar batch — a batch costs one
        queue slot no matter how many rows it carries); ``False`` (and a
        counter tick) when the queue is full past ``timeout`` — bounded
        memory beats unbounded lag."""
        try:
            if timeout is None:
                self._q.put_nowait(record)
            else:
                self._q.put(record, timeout=timeout)
            return True
        except queue.Full:
            _obs.counter_inc("serve.records_rejected")
            return False

    def put_control(self, token: _FlushToken, timeout: Optional[float] = None) -> bool:
        """Enqueue a control token (flush sentinel).  Untimed by default —
        tokens are rare and the caller is waiting on the round-trip anyway —
        but callers that must re-check consumer liveness (a dead writer
        never drains a full queue) pass ``timeout`` and retry on ``False``."""
        try:
            if timeout is None:
                self._q.put(token)
            else:
                self._q.put(token, timeout=timeout)
        except queue.Full:
            return False
        return True

    def get(self, timeout: float) -> Any:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def depth(self) -> int:
        return self._q.qsize()


def _pow2_chunks(n: int, cap: int) -> List[int]:
    """Greedy power-of-two decomposition of ``n``, capped at ``cap`` — the
    fixed shape-set plain jobs dispatch in."""
    out: List[int] = []
    while n >= cap:
        out.append(cap)
        n -= cap
    size = cap >> 1
    while n > 0 and size > 0:
        if n >= size:
            out.append(size)
            n -= size
        size >>= 1
    return out


class BlockBatcher:
    """Per-job row accumulator that emits static-shape ``update`` dispatches.

    Buffered rows live as columnar *segments* (one per staged row-batch or
    :class:`ColumnBatch`).  A **forced** flush dispatches everything —
    full blocks, then the pow2 tail (plain) or one padded block
    (multistream) — bit-identical to dispatching the rows directly.  A
    **non-forced** flush dispatches only whole ``block_rows`` blocks and
    *carries* the residue, so steady-state traffic costs exactly one
    full-block dispatch per ``block_rows`` rows instead of up to
    ``log2(block_rows)+1`` tail dispatches per flush.  ``age`` lets the
    consumer force a flush only when the carried rows have actually gone
    stale, preserving the ingest-to-state latency bound.
    """

    def __init__(self, job: EvalJob, block_rows: int = 256) -> None:
        if int(block_rows) < 1:
            raise MetricsTPUUserError(f"block_rows must be >= 1, got {block_rows}")
        # power-of-two capacity keeps the plain-job chunk set nested
        b = int(block_rows)
        if b & (b - 1):
            raise MetricsTPUUserError(
                f"block_rows must be a power of two, got {block_rows}"
            )
        self.job = job
        self.block_rows = b
        self._rows: List[Tuple[Any, ...]] = []
        self._ids: List[int] = []
        # carried columnar segments: (cols, ids-or-None, n) in arrival order
        self._segments: List[Tuple[List[np.ndarray], Optional[np.ndarray], int]] = []
        self._segments_n = 0
        self._oldest: Optional[float] = None  # monotonic enqueue time, oldest row
        self.rows_padded = 0  # host counter: pad rows ever dispatched

    def __len__(self) -> int:
        return self._segments_n + len(self._rows)

    def age(self, now: Optional[float] = None) -> float:
        """Seconds the oldest buffered row has waited (0.0 when empty)."""
        if self._oldest is None:
            return 0.0
        return (time.monotonic() if now is None else now) - self._oldest

    def add(self, record: Record) -> None:
        if self.job.is_multistream:
            if record.stream_id is None:
                raise MetricsTPUUserError(
                    f"job {self.job.name!r} is multistream; records need a stream_id"
                )
            self._ids.append(int(record.stream_id))
        elif record.stream_id is not None:
            raise MetricsTPUUserError(
                f"job {self.job.name!r} is {self.job.kind}; stream_id must be None"
            )
        self._rows.append(record.values)
        if self._oldest is None:
            self._oldest = time.monotonic()
        if len(self) >= self.block_rows:
            # a full block dispatches as-is; force would add nothing
            self.flush(force=False)

    def extend_columns(
        self, cols: Sequence[np.ndarray], stream_ids: Optional[np.ndarray] = None
    ) -> int:
        """Buffer ``n`` already-columnar rows without per-record objects.

        ``cols`` are views or arrays with a shared leading dim ``n``; they
        are staged as one segment (no copy) and dispatched on the next
        block boundary.  Returns ``n``.
        """
        if self.job.is_multistream:
            if stream_ids is None:
                raise MetricsTPUUserError(
                    f"job {self.job.name!r} is multistream; batches need stream_ids"
                )
        elif stream_ids is not None:
            raise MetricsTPUUserError(
                f"job {self.job.name!r} is {self.job.kind}; stream_ids must be None"
            )
        cols = [np.asarray(c) for c in cols]
        if not cols:
            raise MetricsTPUUserError("ColumnBatch needs at least one column")
        n = int(cols[0].shape[0]) if cols[0].ndim else -1
        if n < 0 or any(c.ndim == 0 or c.shape[0] != n for c in cols):
            raise MetricsTPUUserError(
                f"job {self.job.name!r}: columns must share one leading dim"
            )
        ids = None
        if stream_ids is not None:
            ids = np.asarray(stream_ids, np.int32).reshape(-1)
            if ids.shape[0] != n:
                raise MetricsTPUUserError(
                    f"job {self.job.name!r}: stream_ids length {ids.shape[0]} != {n}"
                )
        if n == 0:
            return 0
        self._stage_rows()  # keep arrival order when add() rows are pending
        self._segments.append((cols, ids, n))
        self._segments_n += n
        if self._oldest is None:
            self._oldest = time.monotonic()
        if len(self) >= self.block_rows:
            self.flush(force=False)
        return n

    # ------------------------------------------------------------- dispatch
    def _stack(self, rows: Sequence[Tuple[Any, ...]]) -> List[np.ndarray]:
        arity = len(rows[0])
        if any(len(r) != arity for r in rows):
            raise MetricsTPUUserError(
                f"job {self.job.name!r} received records of mixed arity"
            )
        return [np.stack([np.asarray(r[i]) for r in rows]) for i in range(arity)]

    def _stage_rows(self) -> None:
        """Move the row-major add() buffer into one columnar segment.  The
        rows are consumed before stacking so a malformed batch is dropped
        (and counted by the caller), never retried forever."""
        rows, self._rows = self._rows, []
        ids, self._ids = self._ids, []
        if not rows:
            return
        cols = self._stack(rows)
        seg_ids = np.asarray(ids, np.int32) if self.job.is_multistream else None
        self._segments.append((cols, seg_ids, len(rows)))
        self._segments_n += len(rows)

    def _take(self, count: int) -> Tuple[List[np.ndarray], Optional[np.ndarray]]:
        """Pop the first ``count`` buffered rows as one columnar batch.
        Whole segments pass through as views; a straddling segment is split
        by slicing (still views) — at most one concatenate per flush."""
        parts: List[Tuple[List[np.ndarray], Optional[np.ndarray]]] = []
        got = 0
        while got < count:
            cols, ids, n = self._segments[0]
            take = min(n, count - got)
            if take == n:
                self._segments.pop(0)
                parts.append((cols, ids))
            else:
                parts.append(
                    ([c[:take] for c in cols], None if ids is None else ids[:take])
                )
                self._segments[0] = (
                    [c[take:] for c in cols],
                    None if ids is None else ids[take:],
                    n - take,
                )
            got += take
        self._segments_n -= count
        if not self._segments:
            self._oldest = None
        if len(parts) == 1:
            return parts[0]
        arity = len(parts[0][0])
        if any(len(p[0]) != arity for p in parts):
            raise MetricsTPUUserError(
                f"job {self.job.name!r} received records of mixed arity"
            )
        cols = [np.concatenate([p[0][i] for p in parts]) for i in range(arity)]
        ids = (
            None
            if parts[0][1] is None
            else np.concatenate([p[1] for p in parts])
        )
        return cols, ids

    def flush(self, force: bool = True) -> int:
        """Dispatch buffered rows; returns the number of rows sent.

        ``force=True`` (the default — what flush tokens, drains and
        checkpoints use) sends everything, tail included.  ``force=False``
        sends only whole blocks and carries the residue for the next flush.
        """
        if self._rows:
            self._stage_rows()
        n = self._segments_n
        if not n:
            return 0
        send = n if force else (n // self.block_rows) * self.block_rows
        if not send:
            return 0
        cols, ids = self._take(send)
        with self.job.lock:
            if self.job.is_multistream:
                start = 0
                while start < send:
                    m = min(self.block_rows, send - start)
                    block = [c[start : start + m] for c in cols]
                    pad = self.block_rows - m
                    if pad:
                        block = [
                            np.concatenate(
                                [c, np.zeros((pad,) + c.shape[1:], c.dtype)]
                            )
                            for c in block
                        ]
                    # -1 is out of [0, num_streams): the on-device scatter
                    # drops the pad rows, so short blocks stay bit-exact;
                    # num_valid (a size-1 array, so it traces instead of
                    # retracing per fill) keeps them out of the
                    # dropped_rows accounting too
                    id_col = np.full((self.block_rows,), -1, np.int32)
                    id_col[:m] = ids[start : start + m]
                    self.job.metric.update(
                        *block,
                        stream_ids=id_col,
                        num_valid=np.asarray([m], np.int32),
                    )
                    self.rows_padded += pad
                    if pad:
                        _obs.counter_inc("serve.rows_padded", pad)
                    self.job.blocks_dispatched += 1
                    _obs.counter_inc("serve.blocks_dispatched", job=self.job.name)
                    start += m
            else:
                start = 0
                for size in _pow2_chunks(send, self.block_rows):
                    self.job.metric.update(*[c[start : start + size] for c in cols])
                    start += size
                    self.job.blocks_dispatched += 1
                    _obs.counter_inc("serve.blocks_dispatched", job=self.job.name)
            self.job.records_ingested += send
        _obs.counter_inc("serve.records_ingested", send)
        return send


class IngestConsumer:
    """The single consumer thread: queue -> batchers -> compiled blocks.

    ``flush_interval`` bounds ingest-to-state latency: a partial block older
    than this flushes even though it is not full.  ``run`` exits when
    ``stop`` is set AND the queue has drained (graceful) or immediately on
    ``kill`` (preemption drill).

    Untrusted rows cannot kill the writer: anything a record raises while
    batching or dispatching (bad dtypes, ragged nested shapes, a stream_id
    that is not an int, ...) is counted, logged, and dropped — the offending
    record (or at worst its buffered batch) is lost, the thread and every
    other job keep going.  A writer that dies anyway (a bug, not bad input)
    is surfaced through ``EvalServer.health()``'s ``consumer_alive``.
    """

    _MAX_ERRORS = 100  # keep the first N messages; count the rest

    def __init__(
        self,
        registry: MetricRegistry,
        ingest_queue: IngestQueue,
        block_rows: int = 256,
        flush_interval: float = 0.05,
        poll_timeout: float = 0.02,
    ) -> None:
        self.registry = registry
        self.queue = ingest_queue
        self.block_rows = int(block_rows)
        self.flush_interval = float(flush_interval)
        self.poll_timeout = float(poll_timeout)
        self.batchers: Dict[str, BlockBatcher] = {
            job.name: BlockBatcher(job, block_rows=block_rows) for job in registry.jobs()
        }
        self.stop = threading.Event()  # graceful: drain, then exit
        self.kill = threading.Event()  # preemption: exit now, drop the queue
        self.errors: List[str] = []
        self.errors_total = 0
        # per-job applied-seq watermarks (WAL mode): the highest frame seq
        # whose rows this consumer has folded (or deterministically
        # dropped).  Only this thread writes after seeding; checkpoint
        # hold-tokens snapshot it at a quiesced drain point.
        self.wal_marks: Dict[str, int] = {}

    def record_error(self, message: str) -> None:
        """Append to the bounded error log (a malformed-record flood must
        not grow host memory without limit in a long-running service)."""
        self.errors_total += 1
        if len(self.errors) < self._MAX_ERRORS:
            self.errors.append(message)

    def flush_all(self, stale_after: Optional[float] = None) -> int:
        """Flush every batcher.  A batch that fails to dispatch is dropped
        and counted — it must not wedge the writer or starve other jobs.

        ``stale_after=None`` (tokens, drains, checkpoints) forces every
        tail out.  With a threshold (the interval flush), a batcher only
        forces its sub-block tail once its oldest row has waited that
        long; younger residues carry forward so steady-state traffic
        dispatches full blocks only.
        """
        total = 0
        now = time.monotonic() if stale_after is not None else 0.0
        for batcher in self.batchers.values():
            force = stale_after is None or batcher.age(now) >= stale_after
            try:
                total += batcher.flush(force=force)
            except Exception as err:  # noqa: BLE001 — untrusted rows reach np.stack/update
                _obs.counter_inc("serve.flush_failures", job=batcher.job.name)
                self.record_error(
                    f"flush of job {batcher.job.name!r} dropped a batch: "
                    f"{type(err).__name__}: {err}"
                )
        return total

    def _batcher_for(self, name: str) -> Optional[BlockBatcher]:
        batcher = self.batchers.get(name)
        if name not in self.registry:
            if batcher is not None:
                # the job was retired (elastic resize moved it away): drop
                # the inert batcher — only this (consumer) thread ever
                # mutates the map — so rows stop folding into dead state
                del self.batchers[name]
            return None
        job = self.registry[name]
        if batcher is None or batcher.job is not job:
            # a job registered after the consumer came up (or re-registered
            # with a fresh EvalJob by a migration commit) still routes
            batcher = self.batchers[name] = BlockBatcher(
                job, block_rows=self.block_rows
            )
        return batcher

    def _consume(self, item: Any, last_flush: float, now: float) -> float:
        if isinstance(item, _FlushToken):
            self.flush_all()
            if item.hold:
                # quiesce for a watermark-exact checkpoint: the marks
                # captured here describe precisely the rows the flush just
                # folded, and nothing further folds until the checkpointer
                # finishes encoding and releases us (bounded wait — see
                # _FlushToken)
                item.marks = dict(self.wal_marks)
                item.done.set()
                item.release.wait(_FlushToken._HOLD_TIMEOUT)
            else:
                item.done.set()
            return now
        try:
            batcher = self._batcher_for(item.job)
            if batcher is None:
                _obs.counter_inc("serve.records_unroutable")
                self.record_error(f"unknown job {item.job!r}")
                return last_flush
            if isinstance(item, ColumnBatch):
                batcher.extend_columns(item.cols, item.stream_ids)
            else:
                batcher.add(item)
        except MetricsTPUUserError as err:
            _obs.counter_inc("serve.records_malformed")
            self.record_error(str(err))
        except Exception as err:  # noqa: BLE001 — POST /ingest data is untrusted
            _obs.counter_inc("serve.records_malformed")
            self.record_error(f"{type(err).__name__}: {err}")
        finally:
            # the watermark advances even when the batch was dropped
            # (malformed / retired job): a replay of the same frame would
            # drop it identically, so "applied" means "its effect — possibly
            # nothing — is in this state", keeping replay exactly-once and
            # segment truncation unwedged
            seq = getattr(item, "seq", None)
            if seq is not None:
                job = getattr(item, "job", None)
                if job is not None and seq > self.wal_marks.get(job, -1):
                    self.wal_marks[job] = int(seq)
        return last_flush

    def run(self) -> None:
        import time as _time

        last_flush = _time.monotonic()
        while not self.kill.is_set():
            item = self.queue.get(timeout=self.poll_timeout)
            now = _time.monotonic()
            if item is not None:
                last_flush = self._consume(item, last_flush, now)
            elif self.stop.is_set():
                break  # queue drained after stop: graceful exit
            # the latency bound applies under steady trickle too, not just
            # when the queue goes idle; a tail younger than the interval is
            # carried (full-block dispatches only), so the worst-case
            # ingest-to-state latency is ~2x flush_interval
            if now - last_flush >= self.flush_interval:
                if self.flush_all(stale_after=self.flush_interval):
                    _obs.counter_inc("serve.interval_flushes")
                last_flush = now
        if not self.kill.is_set():
            self.flush_all()
