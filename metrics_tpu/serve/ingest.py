"""Ingestion pipeline: bounded record queue -> fixed-shape compiled blocks.

Producers call :meth:`IngestQueue.put` with one :class:`Record` per input
row; the single consumer thread (driven by the server) drains the queue and
micro-batches rows per job into **static-shape dispatches** — the same
discipline ``tools/shape_lint.py`` enforces on the streaming/multistream
state math, applied to the serving path:

* **Multistream jobs** fill fixed-capacity padded blocks: rows stack into a
  ``(block_rows, ...)`` block, short blocks pad with zero rows whose
  ``stream_id`` is ``-1`` — out of range, so the scatter drops them on
  device and the padding provably never touches metric state.  Every block
  is ONE compiled ``update`` with ONE shape, so the jitted program never
  retraces no matter how traffic arrives.
* **Plain jobs** (no stream routing, so no drop lane to pad into) decompose
  each flush into power-of-two chunks capped at ``block_rows``: at most
  ``log2(block_rows)+1`` distinct shapes ever reach the compiler, and every
  row is dispatched exactly once — bit-identical to calling ``update``
  directly with the same rows.

Back-pressure is the queue bound: a full queue rejects the record (counted
in ``serve.records_rejected``) instead of stalling the producer or growing
without limit.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.obs import core as _obs
from metrics_tpu.serve.registry import EvalJob, MetricRegistry
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["Record", "IngestQueue", "BlockBatcher", "IngestConsumer"]


class Record(NamedTuple):
    """One input row for one job.

    ``values`` are the job metric's positional update arguments for a single
    row (scalars or fixed-shape per-row arrays — every record of a job must
    agree on shapes, the static-shape contract).  ``stream_id`` routes the
    row on multistream jobs and must be ``None`` on plain jobs.
    """

    job: str
    values: Tuple[Any, ...]
    stream_id: Optional[int] = None


class _FlushToken:
    """Sentinel a producer enqueues to observe a drain point: the consumer
    flushes every batcher, then sets the event."""

    def __init__(self) -> None:
        self.done = threading.Event()


class IngestQueue:
    """Bounded MPSC record queue with rejection accounting."""

    def __init__(self, capacity: int = 4096) -> None:
        if int(capacity) < 1:
            raise MetricsTPUUserError(f"queue capacity must be >= 1, got {capacity}")
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=int(capacity))
        self.capacity = int(capacity)

    def put(self, record: Record, timeout: Optional[float] = None) -> bool:
        """Enqueue one record; ``False`` (and a counter tick) when the queue
        is full past ``timeout`` — bounded memory beats unbounded lag."""
        try:
            if timeout is None:
                self._q.put_nowait(record)
            else:
                self._q.put(record, timeout=timeout)
            return True
        except queue.Full:
            _obs.counter_inc("serve.records_rejected")
            return False

    def put_control(self, token: _FlushToken) -> None:
        """Control tokens (flush sentinels) may block: they are rare and the
        caller is waiting on the round-trip anyway."""
        self._q.put(token)

    def get(self, timeout: float) -> Any:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def depth(self) -> int:
        return self._q.qsize()


def _pow2_chunks(n: int, cap: int) -> List[int]:
    """Greedy power-of-two decomposition of ``n``, capped at ``cap`` — the
    fixed shape-set plain jobs dispatch in."""
    out: List[int] = []
    while n >= cap:
        out.append(cap)
        n -= cap
    size = cap >> 1
    while n > 0 and size > 0:
        if n >= size:
            out.append(size)
            n -= size
        size >>= 1
    return out


class BlockBatcher:
    """Per-job row accumulator that emits static-shape ``update`` dispatches."""

    def __init__(self, job: EvalJob, block_rows: int = 256) -> None:
        if int(block_rows) < 1:
            raise MetricsTPUUserError(f"block_rows must be >= 1, got {block_rows}")
        # power-of-two capacity keeps the plain-job chunk set nested
        b = int(block_rows)
        if b & (b - 1):
            raise MetricsTPUUserError(
                f"block_rows must be a power of two, got {block_rows}"
            )
        self.job = job
        self.block_rows = b
        self._rows: List[Tuple[Any, ...]] = []
        self._ids: List[int] = []
        self.rows_padded = 0  # host counter: pad rows ever dispatched

    def __len__(self) -> int:
        return len(self._rows)

    def add(self, record: Record) -> None:
        if self.job.is_multistream:
            if record.stream_id is None:
                raise MetricsTPUUserError(
                    f"job {self.job.name!r} is multistream; records need a stream_id"
                )
            self._ids.append(int(record.stream_id))
        elif record.stream_id is not None:
            raise MetricsTPUUserError(
                f"job {self.job.name!r} is {self.job.kind}; stream_id must be None"
            )
        self._rows.append(record.values)
        if len(self._rows) >= self.block_rows:
            self.flush()

    # ------------------------------------------------------------- dispatch
    def _stack(self, rows: Sequence[Tuple[Any, ...]]) -> List[np.ndarray]:
        arity = len(rows[0])
        if any(len(r) != arity for r in rows):
            raise MetricsTPUUserError(
                f"job {self.job.name!r} received records of mixed arity"
            )
        return [np.stack([np.asarray(r[i]) for r in rows]) for i in range(arity)]

    def flush(self) -> int:
        """Dispatch everything buffered; returns the number of rows sent."""
        if not self._rows:
            return 0
        rows, self._rows = self._rows, []
        ids, self._ids = self._ids, []
        cols = self._stack(rows)
        n = len(rows)
        with self.job.lock:
            if self.job.is_multistream:
                pad = self.block_rows - n
                padded = [
                    np.concatenate(
                        [c, np.zeros((pad,) + c.shape[1:], c.dtype)]
                    ) if pad else c
                    for c in cols
                ]
                # -1 is out of [0, num_streams): the on-device scatter drops
                # the pad rows, so short blocks stay bit-exact
                id_col = np.full((self.block_rows,), -1, np.int32)
                id_col[:n] = np.asarray(ids, np.int32)
                self.job.metric.update(*padded, stream_ids=id_col)
                self.rows_padded += pad
                if pad:
                    _obs.counter_inc("serve.rows_padded", pad)
                self.job.blocks_dispatched += 1
                _obs.counter_inc("serve.blocks_dispatched", job=self.job.name)
            else:
                start = 0
                for size in _pow2_chunks(n, self.block_rows):
                    self.job.metric.update(*[c[start : start + size] for c in cols])
                    start += size
                    self.job.blocks_dispatched += 1
                    _obs.counter_inc("serve.blocks_dispatched", job=self.job.name)
            self.job.records_ingested += n
        _obs.counter_inc("serve.records_ingested", n)
        return n


class IngestConsumer:
    """The single consumer thread: queue -> batchers -> compiled blocks.

    ``flush_interval`` bounds ingest-to-state latency: a partial block older
    than this flushes even though it is not full.  ``run`` exits when
    ``stop`` is set AND the queue has drained (graceful) or immediately on
    ``kill`` (preemption drill).
    """

    def __init__(
        self,
        registry: MetricRegistry,
        ingest_queue: IngestQueue,
        block_rows: int = 256,
        flush_interval: float = 0.05,
        poll_timeout: float = 0.02,
    ) -> None:
        self.registry = registry
        self.queue = ingest_queue
        self.flush_interval = float(flush_interval)
        self.poll_timeout = float(poll_timeout)
        self.batchers: Dict[str, BlockBatcher] = {
            job.name: BlockBatcher(job, block_rows=block_rows) for job in registry.jobs()
        }
        self.stop = threading.Event()  # graceful: drain, then exit
        self.kill = threading.Event()  # preemption: exit now, drop the queue
        self.errors: List[str] = []

    def flush_all(self) -> int:
        return sum(b.flush() for b in self.batchers.values())

    def _consume(self, item: Any, last_flush: float, now: float) -> float:
        if isinstance(item, _FlushToken):
            self.flush_all()
            item.done.set()
            return now
        try:
            self.batchers[item.job].add(item)
        except KeyError:
            _obs.counter_inc("serve.records_unroutable")
            self.errors.append(f"unknown job {item.job!r}")
        except MetricsTPUUserError as err:
            _obs.counter_inc("serve.records_malformed")
            self.errors.append(str(err))
        return last_flush

    def run(self) -> None:
        import time as _time

        last_flush = _time.monotonic()
        while not self.kill.is_set():
            item = self.queue.get(timeout=self.poll_timeout)
            now = _time.monotonic()
            if item is not None:
                last_flush = self._consume(item, last_flush, now)
            elif self.stop.is_set():
                break  # queue drained after stop: graceful exit
            # the latency bound applies under steady trickle too, not just
            # when the queue goes idle
            if now - last_flush >= self.flush_interval:
                if self.flush_all():
                    _obs.counter_inc("serve.interval_flushes")
                last_flush = now
        if not self.kill.is_set():
            self.flush_all()
