"""EvalServer: the long-running process tying the serve pieces together.

Thread layout (one process, no new dependencies):

* **consumer** — the single writer; drains the :class:`IngestQueue` into
  per-job :class:`BlockBatcher` dispatches (see ``ingest.py``).
* **http** — a ``ThreadingHTTPServer`` answering ``/healthz``, ``/metrics``,
  ``/query`` and ``POST /ingest``; read paths take per-job locks only.
* **durability** (optional) — polls :meth:`CheckpointManager.save_due` and
  snapshots the whole registry when the max-staleness budget runs out or an
  operator armed :meth:`~CheckpointManager.request_save`.

Lifecycle:

* :meth:`start` restores from the newest committed checkpoint when one
  exists (restore-on-start), then brings the threads up.
* :meth:`stop` is the graceful path: mark draining (``/healthz`` flips to
  503, new records are rejected), let the consumer drain the queue and
  flush every partial block, take one final checkpoint, then shut the HTTP
  server down.  A drained-and-stopped server loses nothing.
* :meth:`kill` is the preemption drill: drop the queue and stop without a
  final checkpoint — restart recovery is the durability loop's last commit.
"""
# analyze: skip-file[serve-blocking] -- this module IS the durability layer:
# it owns the checkpoint imports and the save/restore calls that the
# request-path modules (httpd/ingest/registry/traffic) are banned from making.

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from metrics_tpu.checkpoint.manager import (
    CheckpointManager,
    apply_metric_transfer,
    decode_stream_span,
    encode_metric_transfer,
    encode_stream_span,
)
from metrics_tpu.multistream import MultiStreamMetric
from metrics_tpu.obs import core as _obs
from metrics_tpu.serve.httpd import make_http_server
from metrics_tpu.serve.ingest import (
    ColumnBatch,
    IngestConsumer,
    IngestQueue,
    Record,
    _FlushToken,
)
from metrics_tpu.serve.registry import MetricRegistry
from metrics_tpu.utils.exceptions import CheckpointError, MetricsTPUUserError

__all__ = ["ServeConfig", "EvalServer"]


@dataclass
class ServeConfig:
    """Knobs for one :class:`EvalServer`.

    ``port=0`` binds an ephemeral port (read it back from
    :attr:`EvalServer.port` — what the tests and the bench do).
    ``flush_interval`` bounds ingest-to-state latency for partial blocks;
    ``durability_poll`` bounds how stale past ``max_staleness`` a crash can
    strand you, so keep it well under the manager's budget.

    ``wal_exactly_once`` is set by the fleet when durable (WAL-backed)
    ingest is on: checkpoints then quiesce the consumer at the flush point
    (a *hold* token) so the per-job applied-seq watermarks they persist
    describe exactly the snapshot's contents, and ``health()`` exposes the
    live watermarks for failover replay.
    """

    host: str = "127.0.0.1"
    port: int = 0
    queue_capacity: int = 4096
    block_rows: int = 256
    flush_interval: float = 0.05
    poll_timeout: float = 0.02
    drain_timeout: float = 30.0
    durability_poll: float = 0.1
    wal_exactly_once: bool = False


class EvalServer:
    """One registry + one queue + the three service threads."""

    def __init__(
        self,
        registry: MetricRegistry,
        config: Optional[ServeConfig] = None,
        checkpoint_manager: Optional[CheckpointManager] = None,
        builders: Optional[Dict[str, Any]] = None,
    ) -> None:
        if len(registry) == 0:
            raise MetricsTPUUserError("EvalServer needs at least one registered job")
        self.registry = registry
        self.config = config or ServeConfig()
        self.manager = checkpoint_manager
        # job name -> JobSpec-like (.build/.components/.export_top_k): how to
        # construct a fresh metric when an elastic resize migrates a span in
        self._builders: Dict[str, Any] = dict(builders or {})
        self._staged: Dict[str, Any] = {}  # job -> post-resize metric, uncommitted
        self._migrate_lock = threading.Lock()
        try:
            self._migrate_lock.witness_name = "EvalServer._migrate_lock"
        except AttributeError:
            pass
        self.queue = IngestQueue(capacity=self.config.queue_capacity)
        self.consumer = IngestConsumer(
            registry,
            self.queue,
            block_rows=self.config.block_rows,
            flush_interval=self.config.flush_interval,
            poll_timeout=self.config.poll_timeout,
        )
        self.last_checkpoint_step: Optional[int] = None
        self.restored_step: Optional[int] = None
        # WAL seq-dedup floor: highest frame seq ENQUEUED per job (the
        # consumer's wal_marks track the applied floor).  A forward retry
        # or failover replay carrying seq <= this floor is dropped as an
        # idempotent success — the exactly-once half the frontend's
        # durable ack relies on.
        self._wal_enqueued: Dict[str, int] = {}
        self._wal_lock = threading.Lock()
        try:
            self._wal_lock.witness_name = "EvalServer._wal_lock"
        except AttributeError:
            pass
        # watermarks the last committed checkpoint recorded (segment
        # truncation reads these: frames at or below them can never replay)
        self.last_checkpoint_wal_marks: Optional[Dict[str, int]] = None
        self._httpd = None
        self._threads: Dict[str, threading.Thread] = {}
        self._durability_stop = threading.Event()
        self._draining = False
        self._started = False
        self._stopped = False
        self._t0 = time.monotonic()
        self._ckpt_lock = threading.Lock()  # serializes checkpoint_now callers
        try:  # named in the runtime lock-witness graph; raw Locks reject attrs
            self._ckpt_lock.witness_name = "EvalServer._ckpt_lock"
        except AttributeError:
            pass

    # ---------------------------------------------------------------- startup
    def start(self) -> "EvalServer":
        """Restore-on-start, then bring up consumer + HTTP (+ durability)."""
        if self._started:
            raise MetricsTPUUserError("EvalServer.start() called twice")
        self._started = True
        self._t0 = time.monotonic()
        if self.manager is not None and self.manager.latest_step() is not None:
            # build the target OUTSIDE the sweep: its one-time construction
            # takes the same sorted job locks, and nesting that inside
            # locked() would witness reversed acquisition edges
            target = self.registry.checkpoint_target()
            with self.registry.locked():
                result = self.manager.restore(target)
            self.restored_step = self.last_checkpoint_step = result.step
            marks = (result.extra or {}).get("wal_marks")
            if marks:
                # seed both dedup floors BEFORE any thread starts: frames at
                # or below these seqs are inside the restored state, so a
                # replay (or late retry) of them must land as a no-op
                marks = {str(j): int(s) for j, s in marks.items()}
                self.consumer.wal_marks.update(marks)
                self._wal_enqueued.update(marks)
                self.last_checkpoint_wal_marks = dict(marks)
            _obs.counter_inc("serve.restores")
        self._spawn("consumer", self.consumer.run)
        self._httpd = make_http_server(self.config.host, self.config.port, self)
        # a 0.1s shutdown-poll keeps stop()/kill() teardown snappy
        self._spawn("http", lambda: self._httpd.serve_forever(poll_interval=0.1))
        if self.manager is not None:
            self._spawn("durability", self._durability_loop)
        return self

    def _spawn(self, name: str, fn: Any) -> None:
        t = threading.Thread(target=fn, name=f"serve-{name}", daemon=True)
        self._threads[name] = t
        t.start()

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise MetricsTPUUserError("server is not started")
        return self._httpd.server_address[1]

    @property
    def address(self) -> Tuple[str, int]:
        if self._httpd is None:
            raise MetricsTPUUserError("server is not started")
        return (self.config.host, self.port)

    # ---------------------------------------------------------------- ingest
    def submit(
        self,
        job: str,
        values: Tuple[Any, ...],
        stream_id: Optional[int] = None,
        timeout: Optional[float] = None,
    ) -> bool:
        """Enqueue one record; ``False`` when draining or the queue is full."""
        if self._draining:
            _obs.counter_inc("serve.records_rejected", reason="draining")
            return False
        return self.queue.put(Record(job, tuple(values), stream_id), timeout=timeout)

    def submit_columns(
        self,
        job: str,
        cols: Tuple[Any, ...],
        stream_ids: Optional[Any] = None,
        timeout: Optional[float] = None,
        seqs: Optional[Any] = None,
    ) -> bool:
        """Enqueue many rows as ONE columnar batch (one queue slot).

        ``cols`` are pre-stacked ``(n, ...)`` arrays — the zero-copy path
        the sharded frontend forwards ring views through; the consumer
        carries them straight into block dispatches without ever
        materializing per-record Python objects.

        ``seqs`` — ``[(seq_or_None, rows), ...]`` partitioning the rows
        into WAL frames — turns the call idempotent: each framed slice
        whose seq is at or below this worker's enqueue floor is dropped as
        an already-landed duplicate (a forward retry or a failover replay),
        everything else enqueues one :class:`ColumnBatch` per frame and
        advances the floor.  Returns ``True`` only when every frame either
        enqueued or deduped — a partial enqueue reports ``False`` so the
        sender parks and retries the whole ship, and the floor makes that
        retry exactly-once.
        """
        if self._draining:
            _obs.counter_inc("serve.records_rejected", reason="draining")
            return False
        if seqs is None:
            return self.queue.put(
                ColumnBatch(job, tuple(cols), stream_ids), timeout=timeout
            )
        cols = tuple(cols)
        total = int(len(cols[0])) if cols else 0
        if sum(int(n) for _, n in seqs) != total:
            raise MetricsTPUUserError(
                f"seqs cover {sum(int(n) for _, n in seqs)} row(s) but the "
                f"batch has {total}"
            )
        off = 0
        with self._wal_lock:
            for seq, n in seqs:
                n = int(n)
                part = tuple(c[off : off + n] for c in cols)
                part_ids = (
                    None if stream_ids is None else stream_ids[off : off + n]
                )
                off += n
                if seq is not None:
                    seq = int(seq)
                    if seq <= self._wal_enqueued.get(job, -1):
                        _obs.counter_inc("serve.wal_deduped_frames")
                        _obs.counter_inc("serve.wal_deduped_rows", n)
                        continue
                if not self.queue.put(
                    ColumnBatch(job, part, part_ids, seq), timeout=timeout
                ):
                    return False
                if seq is not None:
                    self._wal_enqueued[job] = seq
        return True

    def flush(self, timeout: float = 10.0) -> bool:
        """Force every partial block into metric state and wait for it.

        Round-trips a token through the queue while the consumer is alive
        (so it serializes after everything already enqueued); falls back to
        a direct flush once the consumer has exited.  Every wait is timed
        and liveness is re-checked between them: a writer that dies with the
        queue full makes this return ``False`` within ``timeout`` instead of
        blocking forever on an enqueue nothing will ever drain.
        """
        deadline = time.monotonic() + float(timeout)
        token = _FlushToken()
        consumer = self._threads.get("consumer")
        while consumer is not None and consumer.is_alive():
            remaining = deadline - time.monotonic()
            if remaining <= 0.0:
                return False
            if self.queue.put_control(token, timeout=min(0.5, remaining)):
                return token.done.wait(max(0.0, deadline - time.monotonic()))
        # the single writer has exited: flushing inline cannot race it
        self.consumer.flush_all()
        return True

    def _hold_flush(self, timeout: float = 10.0) -> Optional[_FlushToken]:
        """:meth:`flush`, but freeze the consumer at the drain point.

        Returns the completed hold token — its ``marks`` are the WAL
        watermarks of exactly the state now folded in, and the consumer
        stays parked until the caller sets ``token.release`` — or ``None``
        on timeout.  The caller MUST release the token on every path.
        """
        deadline = time.monotonic() + float(timeout)
        token = _FlushToken(hold=True)
        consumer = self._threads.get("consumer")
        while consumer is not None and consumer.is_alive():
            remaining = deadline - time.monotonic()
            if remaining <= 0.0:
                return None
            if self.queue.put_control(token, timeout=min(0.5, remaining)):
                if token.done.wait(max(0.0, deadline - time.monotonic())):
                    return token
                # pre-release the abandoned token so the consumer, when it
                # eventually reaches it, does not park for a caller that gave up
                token.release.set()
                return None
        # the single writer has exited: nothing can race the encode, so an
        # inline flush plus a direct mark snapshot is already quiesced
        self.consumer.flush_all()
        token.marks = dict(self.consumer.wal_marks)
        token.done.set()
        return token

    # ------------------------------------------------------------ durability
    def checkpoint_now(self, step: Optional[int] = None) -> int:
        """Flush, encode each job under its own lock, commit lock-free.

        The encode holds one brief per-job lock per metric (never the
        registry-wide sweep) and the store writes + commit barrier run with
        NO job lock held, so ``/query`` latency stays flat while a snapshot
        is in flight.  The snapshot is per-job-consistent: each job's state
        is internally coherent, but two jobs may be captured a few records
        apart — the consistency restore actually needs, since every metric
        restores independently.
        """
        if self.manager is None:
            raise MetricsTPUUserError("EvalServer has no CheckpointManager")
        with self._ckpt_lock:
            hold: Optional[_FlushToken] = None
            marks: Optional[Dict[str, int]] = None
            if self.config.wal_exactly_once:
                # hold-flush: the consumer parks between the flush and our
                # release, so the watermarks below describe EXACTLY the rows
                # the encode is about to snapshot — the invariant replay's
                # exactly-once guarantee stands on
                hold = self._hold_flush()  # analyze: ignore[lock-order] -- same contract the flush() chain is baselined under: every put_control and wait inside _hold_flush is deadline-bounded
                if hold is not None:
                    marks = dict(hold.marks)
                else:
                    _obs.counter_inc("serve.checkpoint_flush_timeouts")
                    self.consumer.record_error(
                        "checkpoint hold-flush timed out; snapshot misses "
                        "buffered rows and keeps the previous watermarks"
                    )
                    # degraded but safe-side floor: the previous committed
                    # marks are <= whatever this snapshot contains, so a
                    # replay can duplicate at worst the timed-out window —
                    # never silently drop acked rows
                    marks = dict(self.last_checkpoint_wal_marks or {})
            elif not self.flush():
                # still a consistent snapshot, just missing buffered rows —
                # commit it, but loudly: silent staleness is the real bug
                _obs.counter_inc("serve.checkpoint_flush_timeouts")
                self.consumer.record_error(
                    "checkpoint flush timed out; snapshot misses buffered rows"
                )
            try:
                target = self.registry.checkpoint_target()
                encoded = self.manager.encode_target(
                    target, lock_for=self.registry.lock_for_checkpoint_key
                )
                committed = self.manager.save_now(
                    target,
                    step=step,
                    encoded=encoded,
                    extra={"wal_marks": marks} if marks is not None else None,
                )
            finally:
                if hold is not None:
                    hold.release.set()
            self.last_checkpoint_step = committed
            if marks is not None:
                self.last_checkpoint_wal_marks = marks
        _obs.counter_inc("serve.checkpoints")
        _obs.counter_inc("serve.nonblocking_snapshots")
        return committed

    def _durability_loop(self) -> None:
        poll = self.config.durability_poll
        while not self._durability_stop.wait(timeout=poll):
            if not self.manager.save_due():
                continue
            try:
                self.checkpoint_now()
            except CheckpointError as err:
                # a faulted store must not take the service down: count it,
                # keep serving, retry on the next poll
                _obs.counter_inc("serve.checkpoint_failures")
                self.consumer.record_error(f"checkpoint failed: {err}")

    # ------------------------------------------------------- elastic resize
    def export_span(
        self, job: str, lo: Optional[int] = None, hi: Optional[int] = None
    ) -> Dict[str, Any]:
        """Pack migrating state for one job as a jsonable transfer payload.

        Multistream jobs export the LOCAL row range ``[lo, hi)`` of their
        stacked states; plain jobs export the whole metric (``lo``/``hi``
        ignored).  The coordinator quiesces this worker (forwarder hold +
        flush) first, but a defensive flush here keeps a direct export from
        missing batcher-carried rows.  Exports are pure reads — an aborted
        resize leaves the donor untouched.
        """
        self.flush()
        ejob = self.registry[job]
        with ejob.lock:
            if ejob.is_multistream:
                if lo is None or hi is None:
                    raise MetricsTPUUserError(
                        f"job {job!r} is multistream; export_span needs [lo, hi)"
                    )
                return encode_stream_span(ejob.metric, int(lo), int(hi))
            return encode_metric_transfer(ejob.metric)

    def import_span(
        self,
        job: str,
        width: Optional[int] = None,
        span_lo: int = 0,
        pieces: Tuple[Dict[str, Any], ...] = (),
        plain: bool = False,
    ) -> int:
        """Build this worker's POST-resize metric for ``job`` from transfer
        payloads, staged but not live.

        Multistream: a fresh ``MultiStreamMetric`` of the new span ``width``
        is assembled from donor pieces; each piece's global ``[lo, hi)``
        lands at local rows ``lo - span_lo``.  The pieces must tile the new
        span exactly.  Plain: the donor's whole metric is decoded into a
        fresh instance.  The staged metric only becomes live at
        :meth:`commit_migration` — until then every query reads the
        pre-resize state, so an aborted migration leaves no trace.
        """
        spec = self._builders.get(job)
        if spec is None:
            raise MetricsTPUUserError(
                f"no builder for job {job!r}; this worker cannot host it"
            )
        if plain:
            if len(pieces) != 1:
                raise MetricsTPUUserError(
                    f"plain job {job!r} migrates as exactly one piece, got "
                    f"{len(pieces)}"
                )
            metric = spec.build()
            apply_metric_transfer(metric, pieces[0])
            adopted = 1
        else:
            if width is None or int(width) < 1:
                raise MetricsTPUUserError(
                    f"multistream import for {job!r} needs the new span width"
                )
            metric = MultiStreamMetric(spec.build(), num_streams=int(width))
            covered = 0
            for payload in sorted(pieces, key=lambda p: int(p["lo"])):
                arrays = decode_stream_span(payload)
                covered += metric.adopt_stream_slice(
                    int(payload["lo"]) - int(span_lo), arrays
                )
            if covered != int(width):
                raise MetricsTPUUserError(
                    f"import for {job!r} covered {covered} of {width} rows; "
                    "pieces must tile the new span exactly"
                )
            adopted = covered
        with self._migrate_lock:
            self._staged[job] = metric
        _obs.counter_inc("serve.spans_imported", job=job)
        return adopted

    def commit_migration(self, job: str) -> None:
        """Make the staged post-resize metric live (the worker-local half of
        the epoch flip): an in-place pointer swap under the job lock for a
        job this worker already hosts, or a fresh registration for a plain
        job migrating IN."""
        with self._migrate_lock:
            staged = self._staged.pop(job, None)
        if staged is None:
            raise MetricsTPUUserError(f"no staged migration for job {job!r}")
        if job in self.registry:
            self.registry.rebind(job, staged)
        else:
            spec = self._builders[job]
            self.registry.register(
                job,
                staged,
                components=getattr(spec, "components", None),
                export_top_k=getattr(spec, "export_top_k", 0),
            )
        _obs.counter_inc("serve.migrations_committed", job=job)

    def discard_migration(self, job: Optional[str] = None) -> int:
        """Drop staged state (abort path): the live registry was never
        touched, so this is the whole rollback."""
        with self._migrate_lock:
            if job is not None:
                dropped = 1 if self._staged.pop(job, None) is not None else 0
            else:
                dropped = len(self._staged)
                self._staged.clear()
        return dropped

    def retire_job(self, job: str) -> None:
        """Drop a job whose state migrated to another shard (plain-job
        donor after the epoch flip).  The batcher map is consumer-owned, so
        the inert batcher stays; with the job unregistered, any stray row
        is counted unroutable instead of folding into dead state."""
        self.flush()
        self.registry.unregister(job)
        _obs.counter_inc("serve.jobs_retired", job=job)

    # ----------------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        consumer = self._threads.get("consumer")
        consumer_alive = bool(consumer is not None and consumer.is_alive())
        if self._draining:
            status = "draining"
        elif self._started and not consumer_alive:
            # the writer died: records pile up and silently go nowhere, so
            # /healthz must stop saying "serving" (load balancers route on it)
            status = "failed"
        else:
            status = "serving"
        payload: Dict[str, Any] = {
            "status": status,
            "consumer_alive": consumer_alive,
            "consumer_errors": self.consumer.errors_total,
            "uptime_secs": round(time.monotonic() - self._t0, 3),
            "queue_depth": self.queue.depth(),
            "records_ingested": sum(
                job.records_ingested for job in self.registry.jobs()
            ),
            "jobs": self.registry.describe(),
            "last_checkpoint_step": self.last_checkpoint_step,
            "restored_step": self.restored_step,
        }
        if self.manager is not None:
            payload["checkpoint_staleness_secs"] = round(self.manager.staleness(), 3)
        if self.config.wal_exactly_once:
            # failover replay reads these: the coordinator re-ships every WAL
            # frame past them to a freshly-restored replacement worker
            payload["wal_marks"] = dict(self.consumer.wal_marks)
        return payload

    # --------------------------------------------------------------- shutdown
    def stop(self, final_checkpoint: bool = True) -> Optional[int]:
        """Graceful drain: reject new records, flush everything buffered,
        optionally commit a final checkpoint, then stop the threads.

        Returns the final checkpoint step (``None`` when skipped)."""
        if self._stopped:
            return self.last_checkpoint_step if final_checkpoint else None
        self._draining = True
        # durability loop first, so the final save below cannot race it
        self._stop_thread("durability", self._durability_stop.set)
        self.consumer.stop.set()
        self._stop_thread("consumer", None, timeout=self.config.drain_timeout)
        committed = None
        if final_checkpoint and self.manager is not None:
            committed = self.checkpoint_now()
            _obs.counter_inc("serve.drains")
        self._teardown_http()
        self._stopped = True
        return committed

    def kill(self) -> None:
        """Preemption drill: stop NOW — drop the queue, skip the final
        checkpoint.  Recovery is whatever the durability loop last committed."""
        if self._stopped:
            return
        self._draining = True
        self._stop_thread("durability", self._durability_stop.set)
        self.consumer.kill.set()
        self._stop_thread("consumer", None, timeout=5.0)
        self._teardown_http()
        self._stopped = True
        _obs.counter_inc("serve.kills")

    def _stop_thread(
        self, name: str, signal: Optional[Any], timeout: float = 5.0
    ) -> None:
        t = self._threads.get(name)
        if signal is not None:
            signal()
        if t is not None and t.is_alive():
            t.join(timeout=timeout)

    def _teardown_http(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._stop_thread("http", None)
            self._httpd.server_close()
