"""Durable streaming-eval service: the library as a long-running system.

``metrics_tpu.serve`` turns a set of metrics into a process you can run for
days: a bounded ingestion queue micro-batching records into static-shape
compiled updates, a registry of named eval jobs (plain, windowed,
time-decayed, multistream) with device-side queries, a stdlib HTTP surface
(``/metrics``, ``/query``, ``/healthz``, ``POST /ingest``), and a
durability loop taking preemption-safe checkpoints so a kill at any moment
loses at most the unflushed tail.

Quick start::

    from metrics_tpu import MeanSquaredError
    from metrics_tpu.checkpoint import CheckpointManager
    from metrics_tpu.serve import EvalServer, MetricRegistry, ServeConfig

    registry = MetricRegistry()
    registry.register("mse", MeanSquaredError())
    manager = CheckpointManager("/ckpts/evals", max_staleness=30.0)
    server = EvalServer(registry, ServeConfig(port=9100), manager).start()
    server.submit("mse", (0.9, 1.0))
    # GET :9100/metrics  |  GET :9100/query?job=mse  |  GET :9100/healthz
    server.stop()        # drain + final checkpoint

See ``docs/serving.md`` for the architecture and the soak/kill→restore
drill that backs the durability claim.
"""

from metrics_tpu.serve.ingest import BlockBatcher, IngestConsumer, IngestQueue, Record
from metrics_tpu.serve.registry import EvalJob, MetricRegistry
from metrics_tpu.serve.server import EvalServer, ServeConfig
from metrics_tpu.serve.traffic import JobTraffic, TrafficGenerator, default_traffic

__all__ = [
    "BlockBatcher",
    "EvalJob",
    "EvalServer",
    "IngestConsumer",
    "IngestQueue",
    "JobTraffic",
    "MetricRegistry",
    "Record",
    "ServeConfig",
    "TrafficGenerator",
    "default_traffic",
]
