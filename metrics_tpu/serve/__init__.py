"""Durable streaming-eval service: the library as a long-running system.

``metrics_tpu.serve`` turns a set of metrics into a process you can run for
days: a bounded ingestion queue micro-batching records into static-shape
compiled updates, a registry of named eval jobs (plain, windowed,
time-decayed, multistream) with device-side queries, a stdlib HTTP surface
(``/metrics``, ``/query``, ``/healthz``, ``POST /ingest``), and a
durability loop taking preemption-safe checkpoints so a kill at any moment
loses at most the unflushed tail.

Quick start::

    from metrics_tpu import MeanSquaredError
    from metrics_tpu.checkpoint import CheckpointManager
    from metrics_tpu.serve import EvalServer, MetricRegistry, ServeConfig

    registry = MetricRegistry()
    registry.register("mse", MeanSquaredError())
    manager = CheckpointManager("/ckpts/evals", max_staleness=30.0)
    server = EvalServer(registry, ServeConfig(port=9100), manager).start()
    server.submit("mse", (0.9, 1.0))
    # GET :9100/metrics  |  GET :9100/query?job=mse  |  GET :9100/healthz
    server.stop()        # drain + final checkpoint

One server not enough?  The **sharded fleet** scales the same service
horizontally: a :class:`ShardRouter` span-partitions every multistream
job's stream axis across N workers (plain jobs place whole via a
consistent-hash ring), a :class:`FleetCoordinator` frontend stages ingest
in pre-allocated columnar rings and forwards per shard, scatter-gathers
``top_k`` / ``where`` / ``compute`` with exact single-worker semantics,
and fails a dead shard over to a replacement restored from its own
checkpoint directory.  See ``docs/serving.md``.

Need the ack to survive a SIGKILL?  Set ``FleetSpec.wal_root``: every
accepted batch is framed into a per-shard write-ahead log
(:class:`WalWriter`, group-commit fsync) before the ``200``, checkpoints
carry applied-seq watermarks, and failover replays the log with seq
dedup — exactly-once, no client resends.  See the loss-model table in
``docs/serving.md``.
"""

from metrics_tpu.serve.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    FleetSignals,
    autoscale_step,
)
from metrics_tpu.serve.columnar import ColumnRing
from metrics_tpu.serve.coordinator import (
    FleetCoordinator,
    HTTPShard,
    make_fleet_http_server,
)
from metrics_tpu.serve.fleet import (
    FleetSpec,
    InProcessShard,
    JobSpec,
    LocalFleet,
    build_shard_registry,
)
from metrics_tpu.serve.httpd import PooledHTTPServer
from metrics_tpu.serve.loadgen import ColumnTraffic, LoadReport, run_load, run_process_load
from metrics_tpu.serve.ingest import (
    BlockBatcher,
    ColumnBatch,
    IngestConsumer,
    IngestQueue,
    Record,
)
from metrics_tpu.serve.registry import EvalJob, MetricRegistry
from metrics_tpu.serve.router import (
    HashRing,
    MigrationPlan,
    ShardRouter,
    SpanMove,
    migration_plan,
)
from metrics_tpu.serve.server import EvalServer, ServeConfig
from metrics_tpu.serve.traffic import JobTraffic, TrafficGenerator, default_traffic
from metrics_tpu.serve.wal import (
    WalCorruption,
    WalFrame,
    WalTicket,
    WalWriter,
    inject_wal_fault,
    replay_frames,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "BlockBatcher",
    "ColumnBatch",
    "ColumnRing",
    "ColumnTraffic",
    "LoadReport",
    "EvalJob",
    "EvalServer",
    "FleetCoordinator",
    "FleetSignals",
    "FleetSpec",
    "HTTPShard",
    "HashRing",
    "InProcessShard",
    "IngestConsumer",
    "IngestQueue",
    "JobSpec",
    "JobTraffic",
    "LocalFleet",
    "MetricRegistry",
    "MigrationPlan",
    "PooledHTTPServer",
    "Record",
    "ServeConfig",
    "ShardRouter",
    "SpanMove",
    "TrafficGenerator",
    "WalCorruption",
    "WalFrame",
    "WalTicket",
    "WalWriter",
    "autoscale_step",
    "build_shard_registry",
    "default_traffic",
    "inject_wal_fault",
    "make_fleet_http_server",
    "migration_plan",
    "replay_frames",
    "run_load",
    "run_process_load",
]
