"""Stdlib-only ingest child for the multi-process load generator.

Run BY PATH (``python .../_loadgen_child.py``), never with ``-m``: invoking
it as a module would execute the ``metrics_tpu`` package ``__init__`` and
pay the full JAX import (~seconds) in every child.  By path it is a plain
``__main__`` script whose imports are all stdlib, so a child is live in
tens of milliseconds — the point of process-mode load is measuring the
*server*, not the client's import time.

Emits one JSON line on stdout:
``{"sent": n, "accepted": a, "rejected": r, "elapsed_s": t, "errors": e}``.
"""

import argparse
import json
import random
import time
import urllib.error
import urllib.request


def make_batch(seed, lo, hi, arity, num_streams):
    """Deterministic records for [lo, hi): pure function of the args."""
    rng = random.Random((seed << 20) ^ lo)
    records = []
    for _ in range(hi - lo):
        rec = {"values": [rng.random() for _ in range(arity)]}
        if num_streams:
            rec["stream_id"] = rng.randrange(num_streams)
        records.append(rec)
    return records


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--url", required=True, help="server base, e.g. http://127.0.0.1:9100")
    parser.add_argument("--job", required=True)
    parser.add_argument("--lo", type=int, required=True)
    parser.add_argument("--hi", type=int, required=True)
    parser.add_argument("--batch-rows", type=int, default=256)
    parser.add_argument("--arity", type=int, default=2)
    parser.add_argument("--num-streams", type=int, default=0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--timeout", type=float, default=10.0)
    args = parser.parse_args()

    sent = accepted = rejected = errors = 0
    t0 = time.monotonic()
    for lo in range(args.lo, args.hi, args.batch_rows):
        hi = min(lo + args.batch_rows, args.hi)
        records = make_batch(args.seed, lo, hi, args.arity, args.num_streams)
        body = json.dumps({"job": args.job, "records": records}).encode()
        req = urllib.request.Request(
            args.url + "/ingest",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        sent += len(records)
        try:
            with urllib.request.urlopen(req, timeout=args.timeout) as resp:
                payload = json.loads(resp.read().decode())
        except urllib.error.HTTPError as err:
            raw = err.read()
            try:
                payload = json.loads(raw.decode()) if raw else {}
            except ValueError:
                payload = {}
            if err.code not in (200, 429):
                errors += 1
        except (OSError, ValueError):
            errors += 1
            payload = {}
        accepted += int(payload.get("accepted", 0))
        rejected += int(payload.get("rejected", 0))
    print(
        json.dumps(
            {
                "sent": sent,
                "accepted": accepted,
                "rejected": rejected,
                "elapsed_s": time.monotonic() - t0,
                "errors": errors,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
