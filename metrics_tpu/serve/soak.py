"""Soak harness: sustained traffic, fault injection, kill→restore drill.

The contract being drilled: an :class:`EvalServer` that dies mid-stream and
restarts from its last committed checkpoint, then replays traffic from the
record index that checkpoint covered, ends **bit-identical** to a server
that never died.  That holds because every layer below is deterministic:

* traffic is counter-keyed (record ``i`` is a pure function of the seed),
* block dispatch boundaries depend only on record order (capacity flushes
  plus explicit flushes at fixed record indices — the soak config turns the
  wall-clock interval flush off, since EMA jobs fold per update *call*),
* padded multistream rows are dropped on device, and
* the checkpoint codec round-trips state exactly.

Faults ride along without breaking any of it: a :class:`ChaosStore` tears
an early manifest (the durability path must survive a failed commit and
retry), and a :class:`ChaosBackend` faults an explicit operator sync whose
``on_sync_error="local"`` policy keeps local values — local state is
untouched, so determinism survives.

Used by ``tests/serve/test_soak.py`` (slow tier) and, in miniature, by the
fast server tests; ``python -m metrics_tpu.serve.soak`` runs the drill
standalone.
"""
# analyze: skip-file[serve-blocking] -- the soak harness is an operator/test
# driver, not a request path: it deliberately wires checkpoint stores, chaos
# backends, and explicit operator syncs around the server.

from __future__ import annotations

import json
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from metrics_tpu.checkpoint import CheckpointManager, ChaosStore, LocalStore
from metrics_tpu.obs import core as _obs
from metrics_tpu.parallel import ChaosBackend, LoopbackBackend
from metrics_tpu.multistream import MultiStreamMetric
from metrics_tpu.regression import MeanSquaredError
from metrics_tpu.serve.ingest import BlockBatcher
from metrics_tpu.serve.registry import MetricRegistry
from metrics_tpu.serve.server import EvalServer, ServeConfig
from metrics_tpu.serve.traffic import JobTraffic, TrafficGenerator
from metrics_tpu.streaming import StreamingQuantile, TimeDecayedMetric, WindowedMetric
from metrics_tpu.utils.exceptions import CheckpointError

__all__ = [
    "make_soak_registry",
    "make_soak_traffic",
    "run_uninterrupted",
    "run_drill",
    "ResponsivenessPoller",
    "DrillResult",
    "trees_bitwise_equal",
]

_SOAK_STREAMS = 32


def make_soak_registry(num_streams: int = _SOAK_STREAMS) -> MetricRegistry:
    """One job of every serve kind, all fed by :func:`make_soak_traffic`."""
    registry = MetricRegistry()
    registry.register("mse", MeanSquaredError())
    registry.register(
        "quantiles", StreamingQuantile(q=(0.5, 0.99)), components=("p50", "p99")
    )
    registry.register("window_mse", WindowedMetric(MeanSquaredError(), window_size=4))
    registry.register(
        "decayed_mse", TimeDecayedMetric(MeanSquaredError(), half_life=50.0)
    )
    registry.register(
        "per_tenant",
        MultiStreamMetric(MeanSquaredError(), num_streams=num_streams),
        export_top_k=3,
    )
    return registry


def make_soak_traffic(seed: int = 7, num_streams: int = _SOAK_STREAMS) -> TrafficGenerator:
    """Record schedule matching :func:`make_soak_registry` job-for-job."""
    return TrafficGenerator(
        [
            JobTraffic("mse", arity=2),
            JobTraffic("quantiles", arity=1),
            JobTraffic("window_mse", arity=2),
            JobTraffic("decayed_mse", arity=2),
            JobTraffic("per_tenant", arity=2, num_streams=num_streams, oob_every=13),
        ],
        seed=seed,
    )


def run_uninterrupted(
    traffic: TrafficGenerator,
    n: int,
    flush_points: Tuple[int, ...] = (),
    block_rows: int = 64,
    num_streams: int = _SOAK_STREAMS,
) -> Dict[str, Any]:
    """Feed records ``0..n-1`` straight into batchers — the reference run.

    ``flush_points`` are the record counts at which the drilled server
    flushes (its checkpoints); the reference must flush at the same indices
    because flush boundaries are update-call boundaries for EMA jobs.
    """
    registry = make_soak_registry(num_streams=num_streams)
    batchers = {
        job.name: BlockBatcher(job, block_rows=block_rows) for job in registry.jobs()
    }
    points = set(int(p) for p in flush_points)
    for i in range(n):
        rec = traffic.record(i)
        batchers[rec.job].add(rec)
        if i + 1 in points:
            for b in batchers.values():
                b.flush()
    for b in batchers.values():
        b.flush()
    return registry.compute_all()


class ResponsivenessPoller:
    """Background thread asserting the HTTP surface stays live under load.

    Hits ``/healthz``, ``/metrics`` and ``/query`` in a loop, recording
    per-request latencies and any non-2xx/connection failure.  The drill
    asserts ``failures == []`` — the service never went dark while
    ingesting, checkpointing, or surviving store faults.
    """

    def __init__(self, port: int, query_job: str = "mse", interval: float = 0.02) -> None:
        self._base = f"http://127.0.0.1:{port}"
        self._paths = ["/healthz", "/metrics", f"/query?job={query_job}"]
        self.interval = float(interval)
        self.latencies: List[float] = []
        self.failures: List[str] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="soak-poller", daemon=True
        )

    def start(self) -> "ResponsivenessPoller":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)

    def _run(self) -> None:
        i = 0
        while not self._stop.is_set():
            path = self._paths[i % len(self._paths)]
            i += 1
            t0 = time.monotonic()
            try:
                with urllib.request.urlopen(self._base + path, timeout=5.0) as resp:
                    resp.read()
                    if resp.status >= 400:
                        self.failures.append(f"{path}: HTTP {resp.status}")
            except Exception as err:  # noqa: BLE001 — any failure is a finding
                self.failures.append(f"{path}: {type(err).__name__}: {err}")
            self.latencies.append(time.monotonic() - t0)
            self._stop.wait(self.interval)

    def summary(self) -> Dict[str, Any]:
        lat = sorted(self.latencies)
        pct = lambda q: lat[min(len(lat) - 1, int(q * len(lat)))] if lat else 0.0  # noqa: E731
        return {
            "requests": len(lat),
            "failures": len(self.failures),
            "p50_ms": round(pct(0.50) * 1e3, 3),
            "p99_ms": round(pct(0.99) * 1e3, 3),
        }


def exercise_chaos_sync(registry: MetricRegistry, job: str = "mse") -> Dict[str, Any]:
    """Fire one explicit operator sync through a faulting backend.

    Read paths never sync (the registry forces that off), so the collective
    fault surface in a serving process is exactly this: an operator-driven
    ``sync`` that must degrade to local values instead of corrupting state
    or crashing the service.  Returns the sync report for assertions.
    """
    metric = registry[job].metric
    backend = ChaosBackend(
        LoopbackBackend(), schedule={0: "error"}, fault_exception="sync_error"
    )
    previous = metric.on_sync_error
    metric.on_sync_error = "local"
    try:
        with registry[job].lock:
            # sync_context unsyncs on exit only if the sync actually cached
            # state — robust across the "local"/"skip" fallback paths
            with metric.sync_context(backend=backend):
                pass
    finally:
        metric.on_sync_error = previous
    report = dict(metric.last_sync_report or {})
    return report


def trees_bitwise_equal(a: Any, b: Any) -> bool:
    """Exact (bit-level for floats, NaN==NaN) equality of nested computes."""
    if isinstance(a, dict) or isinstance(b, dict):
        return (
            isinstance(a, dict)
            and isinstance(b, dict)
            and a.keys() == b.keys()
            and all(trees_bitwise_equal(a[k], b[k]) for k in a)
        )
    if isinstance(a, (list, tuple)) or isinstance(b, (list, tuple)):
        return (
            isinstance(a, (list, tuple))
            and isinstance(b, (list, tuple))
            and len(a) == len(b)
            and all(trees_bitwise_equal(x, y) for x, y in zip(a, b))
        )
    fa = np.asarray(a, np.float64)
    fb = np.asarray(b, np.float64)
    return fa.shape == fb.shape and bool(
        np.all(fa.view(np.uint64) == fb.view(np.uint64))
    )


@dataclass
class DrillResult:
    """Everything the kill→restore drill observed, for test assertions."""

    identical: bool
    checkpoint_step: int
    restored_step: int
    final_step: Optional[int]
    checkpoint_failures: int
    chaos_injected: List[Tuple[str, str]]
    sync_report: Dict[str, Any]
    poller_failures: List[str] = field(default_factory=list)
    poller_summary: Dict[str, Any] = field(default_factory=dict)
    baseline: Dict[str, Any] = field(default_factory=dict)
    recovered: Dict[str, Any] = field(default_factory=dict)


def _submit_range(server: EvalServer, traffic: TrafficGenerator, lo: int, hi: int) -> None:
    for rec in traffic.replay(lo, hi):
        ok = server.submit(rec.job, rec.values, stream_id=rec.stream_id, timeout=5.0)
        if not ok:
            raise RuntimeError(f"soak submit rejected at record {rec!r}")


def _checkpoint_with_retry(server: EvalServer, tries: int = 5) -> Tuple[int, int]:
    """Commit a checkpoint, riding out injected store faults; returns
    ``(step, failures)`` — the durability loop's survive-and-retry contract,
    exercised synchronously so the drill stays record-deterministic."""
    failures = 0
    for _ in range(tries):
        try:
            return server.checkpoint_now(), failures
        except CheckpointError:
            failures += 1
            _obs.counter_inc("serve.checkpoint_failures")
    raise CheckpointError(f"checkpoint still failing after {tries} attempts")


def run_drill(
    directory: str,
    n: int = 1500,
    k: int = 900,
    lost_tail: int = 15,
    seed: int = 7,
    block_rows: int = 64,
    num_streams: int = _SOAK_STREAMS,
    store_faults: Optional[List[Tuple[str, str]]] = None,
    poll: bool = True,
) -> DrillResult:
    """The kill→restore drill.

    Phase 1: serve records ``0..k``, checkpoint (through a fault-injecting
    store when ``store_faults`` is set), ingest ``lost_tail`` more records
    that never flush, then ``kill()`` — those tail records are lost, as a
    preemption would lose them.

    Phase 2: a fresh server restores from the checkpoint, replays records
    ``k..n`` (the lost tail re-submitted first, exactly once), and drains
    through the graceful path with a final checkpoint.

    Reference: one uninterrupted run over ``0..n`` with a flush at ``k``.
    ``identical`` is the bit-level comparison of every job's compute.
    """
    if store_faults is None:
        store_faults = [("torn_write", "MANIFEST")]
    config = ServeConfig(
        block_rows=block_rows,
        # wall-clock flushes off: dispatch boundaries must be a function of
        # record indices alone for the bit-exact comparison to be fair
        flush_interval=3600.0,
        queue_capacity=max(4096, n),
    )
    traffic = make_soak_traffic(seed=seed, num_streams=num_streams)

    # ----- phase 1: serve, checkpoint under faults, lose the tail, die
    store = ChaosStore(LocalStore(directory), faults=list(store_faults))
    mgr_a = CheckpointManager(store=store, keep_last=None)
    server_a = EvalServer(
        make_soak_registry(num_streams=num_streams), config, mgr_a
    ).start()
    poller = ResponsivenessPoller(server_a.port).start() if poll else None
    sync_report: Dict[str, Any] = {}
    try:
        _submit_range(server_a, traffic, 0, k)
        server_a.flush()
        sync_report = exercise_chaos_sync(server_a.registry)
        step, ckpt_failures = _checkpoint_with_retry(server_a)
        _submit_range(server_a, traffic, k, k + lost_tail)
    finally:
        if poller is not None:
            poller.stop()
        server_a.kill()

    # ----- phase 2: restore, replay the rest, drain gracefully
    mgr_b = CheckpointManager(directory, keep_last=None)
    server_b = EvalServer(
        make_soak_registry(num_streams=num_streams), config, mgr_b
    ).start()
    poller2 = ResponsivenessPoller(server_b.port).start() if poll else None
    try:
        restored = server_b.restored_step
        if restored != step:
            raise RuntimeError(f"restored step {restored!r} != committed step {step}")
        _submit_range(server_b, traffic, k, n)
    finally:
        if poller2 is not None:
            poller2.stop()
    final_step = server_b.stop(final_checkpoint=True)
    recovered = server_b.registry.compute_all()

    # ----- reference: never died, flushed at the same record index
    baseline = run_uninterrupted(
        traffic, n, flush_points=(k,), block_rows=block_rows, num_streams=num_streams
    )

    failures = list(poller.failures if poller else []) + list(
        poller2.failures if poller2 else []
    )
    summary: Dict[str, Any] = {}
    if poller is not None and poller2 is not None:
        summary = {"phase1": poller.summary(), "phase2": poller2.summary()}
    return DrillResult(
        identical=trees_bitwise_equal(baseline, recovered),
        checkpoint_step=step,
        restored_step=restored,
        final_step=final_step,
        checkpoint_failures=ckpt_failures,
        chaos_injected=list(store.injected),
        sync_report=sync_report,
        poller_failures=failures,
        poller_summary=summary,
        baseline=baseline,
        recovered=recovered,
    )


def main() -> int:
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        result = run_drill(tmp)
    payload = {
        "identical": result.identical,
        "checkpoint_step": result.checkpoint_step,
        "restored_step": result.restored_step,
        "final_step": result.final_step,
        "checkpoint_failures": result.checkpoint_failures,
        "chaos_injected": result.chaos_injected,
        "poller": result.poller_summary,
        "poller_failures": result.poller_failures[:5],
    }
    print(json.dumps(payload, indent=2, default=str))
    return 0 if result.identical and not result.poller_failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
