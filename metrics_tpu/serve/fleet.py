"""Fleet assembly: shard registries, in-process workers, checkpointed respawn.

This module is the **construction** side of the sharded serve tier — the
coordinator (``coordinator.py``) stays a pure request path and never
imports it.  Here live:

* :func:`build_shard_registry` — the per-shard registry: every multistream
  job re-registers at its span's width (same name, narrower stream axis);
  a plain job registers only on the one shard the router's hash ring owns
  it on.
* :class:`InProcessShard` — the duck-typed handle the coordinator drives
  when the whole fleet lives in one process (the fast tests and the
  bench); it copies ring views at the enqueue boundary, because the
  coordinator's ``commit`` frees the slots the views alias.
* :class:`LocalFleet` — N in-process :class:`EvalServer` workers, one
  coordinator, per-shard checkpoint directories, and the ``respawn``
  callback that makes :meth:`FleetCoordinator.failover` work: a
  replacement worker restores the dead shard's latest committed snapshot
  on start (restore-on-start is EvalServer's normal boot path), then the
  rows parked in the shard's staging ring drain into it.

Determinism note for the failover drill: with interval flushing disabled
(``flush_interval`` large), block dispatch boundaries depend only on each
shard's cumulative row count — the carry-buffered
:class:`~metrics_tpu.serve.ingest.BlockBatcher` dispatches whole blocks no
matter how the rows were framed in flight — so a kill → respawn → drain
run is bitwise identical to an uninterrupted one.
"""
# analyze: skip-file[serve-blocking] -- the fleet layer owns worker
# construction and checkpoint restore-on-respawn: it wires the durability
# machinery the request-path modules (router/columnar/coordinator) are
# banned from touching.

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.checkpoint.manager import (
    CheckpointManager,
    shard_checkpoint_directory,
)
from metrics_tpu.multistream import MultiStreamMetric
from metrics_tpu.obs import core as _obs
from metrics_tpu.serve.coordinator import FleetCoordinator
from metrics_tpu.serve.registry import MetricRegistry, _to_jsonable
from metrics_tpu.serve.router import ShardRouter
from metrics_tpu.serve.server import EvalServer, ServeConfig
from metrics_tpu.serve.wal import WalWriter
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = [
    "JobSpec",
    "FleetSpec",
    "build_router",
    "build_shard_registry",
    "InProcessShard",
    "LocalFleet",
]


@dataclass
class JobSpec:
    """One job, fleet-wide.

    ``build`` returns a FRESH metric instance each call — every shard (and
    every respawn) constructs its own state.  ``num_streams`` is the
    *global* stream axis; ``None`` marks a plain job placed whole by the
    hash ring.
    """

    name: str
    build: Callable[[], Any]
    num_streams: Optional[int] = None
    components: Optional[Sequence[str]] = None
    export_top_k: int = 0


@dataclass
class FleetSpec:
    """Everything needed to stand a fleet up (or respawn one shard of it).

    ``wal_root`` switches ingest from queue-ack to durable-ack: each shard
    gets a segmented :class:`~metrics_tpu.serve.wal.WalWriter` under
    ``wal_root/shard_NNNN``, workers run with ``wal_exactly_once`` (seq
    dedup + applied-seq watermarks in their checkpoints), and failover
    replays the log — see ``docs/serving.md``'s loss-model table.
    ``wal_fsync=False`` keeps the frames (page cache durability, enough
    for SIGKILL drills) but skips the disk barrier; the bench sweep uses
    it to price the fsync itself.
    """

    num_shards: int
    jobs: Sequence[JobSpec]
    checkpoint_root: Optional[str] = None
    server_config: ServeConfig = field(default_factory=ServeConfig)
    vnodes: int = 64
    ring_capacity: int = 8192
    ingest_dtype: Any = np.float32
    max_staleness: Optional[float] = None  # arms per-shard durability loops
    query_timeout: float = 30.0
    wal_root: Optional[str] = None
    wal_segment_bytes: int = 4 << 20
    wal_fsync: bool = True


def build_router(spec: FleetSpec) -> ShardRouter:
    return ShardRouter(
        spec.num_shards,
        {job.name: job.num_streams for job in spec.jobs},
        vnodes=spec.vnodes,
    )


def build_shard_registry(
    spec: FleetSpec, shard: int, router: ShardRouter
) -> MetricRegistry:
    """The registry shard ``shard`` hosts.

    Multistream jobs keep their fleet-wide name but wrap a fresh metric at
    exactly the span's width — local row ``r`` IS global stream
    ``lo + r``, which is what makes scatter-gather merges exact.  Plain
    jobs register only on their ring-owned shard.
    """
    registry = MetricRegistry()
    for job in spec.jobs:
        if job.num_streams is not None:
            width = router.span_width(job.name, shard)
            registry.register(
                job.name,
                MultiStreamMetric(job.build(), num_streams=width),
                components=job.components,
                export_top_k=min(job.export_top_k, width),
            )
        elif router.owner(job.name) == int(shard):
            registry.register(
                job.name,
                job.build(),
                components=job.components,
            )
    if len(registry) == 0:
        raise MetricsTPUUserError(
            f"shard {shard} hosts no jobs (all plain jobs hashed elsewhere); "
            "add a multistream job or shrink the fleet"
        )
    return registry


class InProcessShard:
    """Duck-typed shard handle over an in-process :class:`EvalServer`.

    Mirrors :class:`~metrics_tpu.serve.coordinator.HTTPShard` exactly —
    including the JSON-shaped return values (``_to_jsonable`` floats), so
    a merge computed over in-process handles is bit-identical to one
    computed over the HTTP wire.
    """

    def __init__(self, server: EvalServer) -> None:
        self.server = server
        self.last_checkpoint_wal_marks: Optional[Dict[str, int]] = None

    # --------------------------------------------------------------- ingest
    def ingest_columns(
        self,
        job: str,
        cols: Sequence[np.ndarray],
        stream_ids: Optional[np.ndarray] = None,
        seqs: Optional[Sequence[Tuple[Optional[int], int]]] = None,
    ) -> bool:
        # the coordinator's ring views go stale at commit(): copy at the
        # enqueue boundary (the HTTP handle serializes instead)
        owned = tuple(np.array(c, copy=True) for c in cols)
        ids = None if stream_ids is None else np.array(stream_ids, copy=True)
        return self.server.submit_columns(job, owned, stream_ids=ids, seqs=seqs)

    def ingest_rows(
        self, job: str, rows: Sequence[Tuple[Tuple[Any, ...], Optional[int]]]
    ) -> Tuple[int, int]:
        accepted = rejected = 0
        for values, stream_id in rows:
            ok = self.server.submit(job, values, stream_id=stream_id)
            accepted += int(ok)
            rejected += int(not ok)
        return accepted, rejected

    # ---------------------------------------------------------------- reads
    def compute(self, job: str) -> Any:
        return _to_jsonable(self.server.registry[job].compute())

    def compute_streams(self, job: str, local_ids: Sequence[int]) -> List[Any]:
        return _to_jsonable(
            self.server.registry[job].compute_streams(list(local_ids))
        )

    def top_k(
        self, job: str, k: int, key: Any = None, largest: bool = True
    ) -> Tuple[List[float], List[int]]:
        values, ids = self.server.registry[job].top_k(k, key=key, largest=largest)
        return (
            _to_jsonable(values),
            [int(i) for i in np.asarray(ids).reshape(-1)],
        )

    def where(
        self, job: str, op: str, threshold: float, k: int, key: Any = None
    ) -> Tuple[List[int], int]:
        ids, total = self.server.registry[job].where_op(
            op, float(threshold), k=k, key=key
        )
        kept = [int(i) for i in np.asarray(ids).reshape(-1) if int(i) >= 0]
        return kept, int(np.asarray(total))

    # ------------------------------------------------------------- liveness
    def health(self) -> Dict[str, Any]:
        return self.server.health()

    def flush(self, timeout: float = 10.0) -> bool:
        return self.server.flush(timeout=timeout)

    def checkpoint(self) -> int:
        step = self.server.checkpoint_now()
        # duck-parity with HTTPShard: expose the committed watermarks so
        # the owner of the shard's WalWriter can truncate covered segments
        self.last_checkpoint_wal_marks = self.server.last_checkpoint_wal_marks
        return step

    # ------------------------------------------------------------- migration
    def _require_live(self) -> None:
        # the HTTP handle gets connection-refused from a dead worker; the
        # in-process handle must fail the same way, so a kill mid-migration
        # aborts the resize instead of silently reading a corpse's registry
        if self.server._stopped:
            raise MetricsTPUUserError("shard worker is stopped")

    def migrate_out(
        self, job: str, lo: Optional[int] = None, hi: Optional[int] = None
    ) -> Dict[str, Any]:
        self._require_live()
        return self.server.export_span(job, lo=lo, hi=hi)

    def migrate_in(
        self,
        job: str,
        width: Optional[int] = None,
        span_lo: int = 0,
        pieces: Sequence[Dict[str, Any]] = (),
        plain: bool = False,
    ) -> int:
        self._require_live()
        return self.server.import_span(
            job, width=width, span_lo=span_lo, pieces=tuple(pieces), plain=plain
        )

    def commit_migration(self, job: str) -> None:
        self._require_live()
        self.server.commit_migration(job)

    def discard_migration(self, job: Optional[str] = None) -> int:
        # abort path: stays callable on a dead worker (nothing to undo there)
        return self.server.discard_migration(job)

    def retire_job(self, job: str) -> None:
        self._require_live()
        self.server.retire_job(job)


class LocalFleet:
    """N in-process workers + one coordinator (tests and the bench).

    Subprocess workers (``python -m metrics_tpu.serve.worker``) speak the
    same protocol through :class:`~metrics_tpu.serve.coordinator.HTTPShard`;
    the slow soak drill wires those up itself.
    """

    def __init__(self, spec: FleetSpec) -> None:
        self.spec = spec
        self.router = build_router(spec)
        self._servers: List[Optional[EvalServer]] = [None] * spec.num_shards
        self.coordinator: Optional[FleetCoordinator] = None
        self._wal: Dict[int, WalWriter] = {}
        self._started = False

    # ------------------------------------------------------------- lifecycle
    def _wal_writer(self, shard: int) -> Optional[WalWriter]:
        """The shard's WalWriter, creating (and recovering) it on demand."""
        if self.spec.wal_root is None:
            return None
        writer = self._wal.get(shard)
        if writer is None:
            writer = WalWriter(
                os.path.join(self.spec.wal_root, f"shard_{shard:04d}"),
                segment_bytes=self.spec.wal_segment_bytes,
                fsync=self.spec.wal_fsync,
            )
            self._wal[shard] = writer
        return writer

    def start(self) -> "LocalFleet":
        if self._started:
            raise MetricsTPUUserError("LocalFleet.start() called twice")
        self._started = True
        handles = []
        for shard in range(self.spec.num_shards):
            self._wal_writer(shard)
            server = self._spawn_server(shard)
            self._servers[shard] = server
            handles.append(InProcessShard(server))
        self.coordinator = FleetCoordinator(
            self.router,
            handles,
            respawn=self._respawn,
            provision=self._provision,
            retire=self._retire_shard,
            ring_capacity=self.spec.ring_capacity,
            ingest_dtype=self.spec.ingest_dtype,
            query_timeout=self.spec.query_timeout,
            wal=self._wal if self.spec.wal_root is not None else None,
        ).start()
        return self

    def _manager(self, shard: int) -> Optional[CheckpointManager]:
        if self.spec.checkpoint_root is None:
            return None
        return CheckpointManager(
            directory=shard_checkpoint_directory(
                self.spec.checkpoint_root, shard
            ),
            max_staleness=self.spec.max_staleness,
        )

    def _spawn_server(
        self, shard: int, router: Optional[ShardRouter] = None
    ) -> EvalServer:
        registry = build_shard_registry(
            self.spec, shard, self.router if router is None else router
        )
        config = replace(
            self.spec.server_config,
            port=0,
            wal_exactly_once=self.spec.wal_root is not None,
        )
        server = EvalServer(
            registry,
            config=config,
            checkpoint_manager=self._manager(shard),
            builders={job.name: job for job in self.spec.jobs},
        )
        # restore-on-start: a respawn after kill_shard() picks the shard's
        # latest committed snapshot right back up
        server.start()
        return server

    def _respawn(self, shard: int) -> InProcessShard:
        # the coordinator's router is the live epoch (it may be ahead of
        # the fleet copy while a resize is mid-flight or has failed)
        router = (
            self.router if self.coordinator is None else self.coordinator.router
        )
        server = self._spawn_server(shard, router=router)
        self._servers[shard] = server
        return InProcessShard(server)

    def _provision(self, shard: int, router: ShardRouter) -> InProcessShard:
        """Coordinator resize callback: stand up a fresh worker for a shard
        a grow adds, registered at the NEW router's spans.  Its zero state
        is replaced by ``migrate_in`` before any row is routed to it."""
        shard = int(shard)
        writer = self._wal_writer(shard)
        if writer is not None and self.coordinator is not None:
            # grown shards get durable ingest too: the coordinator's map
            # was copied at construction, so register the new writer there
            self.coordinator._wal[shard] = writer
        server = self._spawn_server(shard, router=router)
        while len(self._servers) <= shard:
            self._servers.append(None)
        self._servers[shard] = server
        return InProcessShard(server)

    def _retire_shard(self, shard: int) -> None:
        """Coordinator resize callback: stop a worker a shrink removes (or
        a provisioned-then-aborted grow).  No final checkpoint — its state
        already migrated out (or never held anything)."""
        shard = int(shard)
        if shard < len(self._servers) and self._servers[shard] is not None:
            self._servers[shard].stop(final_checkpoint=False)
            self._servers[shard] = None
        writer = self._wal.pop(shard, None)
        if writer is not None:
            if self.coordinator is not None:
                self.coordinator._wal.pop(shard, None)
            writer.close()

    def server(self, shard: int) -> EvalServer:
        srv = self._servers[int(shard)]
        if srv is None:
            raise MetricsTPUUserError(f"shard {shard} is not running")
        return srv

    # -------------------------------------------------------------- drills
    def checkpoint_all(self) -> Dict[int, int]:
        """Flush + snapshot every shard; ``{shard: committed_step}``.

        With a WAL attached, each committed checkpoint's applied-seq
        watermarks then garbage-collect the shard's log: sealed segments
        every watermark covers can never be needed by a replay again.
        """
        steps: Dict[int, int] = {}
        for shard in range(self.spec.num_shards):
            server = self.server(shard)
            steps[shard] = server.checkpoint_now()
            writer = self._wal.get(shard)
            marks = server.last_checkpoint_wal_marks
            if writer is not None and marks:
                writer.truncate_covered(marks)
        return steps

    def kill_shard(self, shard: int) -> None:
        """Preemption: drop the shard's queue, no final checkpoint.  The
        coordinator keeps parking its rows until :meth:`failover`."""
        self.server(shard).kill()
        _obs.counter_inc("serve.fleet_shard_kills", shard=str(shard))

    def failover(self, shard: int) -> InProcessShard:
        if self.coordinator is None:
            raise MetricsTPUUserError("fleet is not started")
        return self.coordinator.failover(shard)

    def resize(
        self,
        num_shards: int,
        timeout: float = 60.0,
        phase_hook: Optional[Callable[[str], None]] = None,
    ) -> Dict[str, Any]:
        """Live resize with a durability floor under the coordinator's
        migration protocol: every shard checkpoints at the **quiesced**
        phase — after the holds and flushes, before any state moves — so a
        worker killed mid-migration loses nothing that ``failover`` plus a
        retried resize cannot restore.  On success the fleet's spec and
        router track the new epoch and a fresh checkpoint pins the resized
        state."""
        if self.coordinator is None:
            raise MetricsTPUUserError("fleet is not started")
        n = int(num_shards)
        durable = self.spec.checkpoint_root is not None

        def _hook(phase: str) -> None:
            if phase == "quiesced" and durable:
                self.checkpoint_all()
            if phase_hook is not None:
                phase_hook(phase)

        summary = self.coordinator.resize(n, timeout=timeout, phase_hook=_hook)
        self.spec = replace(self.spec, num_shards=n)
        self.router = self.coordinator.router
        del self._servers[n:]
        if durable:
            self.checkpoint_all()
        return summary

    def stop(self, final_checkpoint: bool = False) -> None:
        if self.coordinator is not None:
            self.coordinator.stop()
        for shard, server in enumerate(self._servers):
            if server is not None:
                server.stop(final_checkpoint=final_checkpoint)
        for writer in self._wal.values():
            writer.close()
        self._wal = {}
