"""Deterministic traffic for soak tests and the serve bench.

A :class:`TrafficGenerator` is a pure function of ``(seed, index)``: record
``i`` is the same bytes every run and every process, so a kill→restore drill
can replay "records ``k`` onward" after recovering a checkpoint that covered
"records ``0..k``" and compare final state bit-for-bit against an
uninterrupted run of the same schedule.

Records round-robin across the registry's jobs (index → job); multistream
rows cycle through a tenant id pattern with a deliberate out-of-range id
mixed in so the drop lane stays exercised.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.serve.ingest import Record
from metrics_tpu.serve.registry import MetricRegistry
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["JobTraffic", "TrafficGenerator"]


class JobTraffic:
    """Per-job record recipe.

    ``arity`` positional values per record; ``num_streams`` routes records
    over ``[0, num_streams)`` tenant ids when set (with every
    ``oob_every``-th record aimed at an out-of-range id to exercise the
    drop path); values are drawn from a counter-keyed Philox stream so the
    i-th record never depends on how many were drawn before it.
    """

    def __init__(
        self,
        job: str,
        arity: int = 2,
        num_streams: Optional[int] = None,
        oob_every: int = 0,
        integer_values: bool = False,
    ) -> None:
        self.job = job
        self.arity = int(arity)
        self.num_streams = num_streams
        self.oob_every = int(oob_every)
        self.integer_values = bool(integer_values)

    def record(self, seed: int, index: int) -> Record:
        # counter-based: one fresh Philox stream per (seed, index); O(1)
        # random access is what lets the drill replay from any offset
        rng = np.random.Generator(np.random.Philox(key=seed, counter=[0, 0, 0, index]))
        if self.integer_values:
            values: Tuple[Any, ...] = tuple(
                np.int32(rng.integers(0, 10)) for _ in range(self.arity)
            )
        else:
            values = tuple(np.float32(rng.uniform(0.0, 1.0)) for _ in range(self.arity))
        stream_id: Optional[int] = None
        if self.num_streams is not None:
            if self.oob_every and index % self.oob_every == self.oob_every - 1:
                stream_id = self.num_streams + 7  # dropped on device, by design
            else:
                stream_id = int(rng.integers(0, self.num_streams))
        return Record(self.job, values, stream_id)


class TrafficGenerator:
    """Addressable record schedule over several jobs.

    ``record(i)`` is deterministic in ``(seed, i)`` alone.  ``replay(lo,
    hi)`` yields records ``lo..hi-1`` — the uninterrupted run uses
    ``replay(0, n)``, the drill's second half uses ``replay(k, n)``.
    """

    def __init__(self, jobs: Sequence[JobTraffic], seed: int = 0) -> None:
        if not jobs:
            raise MetricsTPUUserError("TrafficGenerator needs at least one JobTraffic")
        self.jobs = list(jobs)
        self.seed = int(seed)

    def record(self, index: int) -> Record:
        spec = self.jobs[index % len(self.jobs)]
        # mix the job slot into the per-record seed so two jobs never see
        # identical value streams
        return spec.record(self.seed * 1_000_003 + (index % len(self.jobs)), index)

    def replay(self, lo: int, hi: int) -> Iterator[Record]:
        for i in range(lo, hi):
            yield self.record(i)

    def records(self, n: int) -> List[Record]:
        return [self.record(i) for i in range(n)]


def default_traffic(registry: MetricRegistry, seed: int = 0) -> TrafficGenerator:
    """A TrafficGenerator matching a registry's jobs: multistream jobs get
    routed ids (with an out-of-range id every 13th record), others plain
    rows; arity follows each job's registered metric where discoverable
    (falls back to 2 positional values)."""
    specs = []
    for job in registry.jobs():
        if job.is_multistream:
            specs.append(
                JobTraffic(
                    job.name,
                    arity=2,
                    num_streams=job.metric.num_streams,
                    oob_every=13,
                )
            )
        else:
            specs.append(JobTraffic(job.name, arity=2))
    return TrafficGenerator(specs, seed=seed)


__all__.append("default_traffic")
