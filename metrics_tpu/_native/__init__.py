"""On-demand-built C++ host kernels (ctypes), with pure-Python fallbacks.

The TPU compute path is XLA; these cover the host-sequential algorithms the
reference keeps in pure Python (edit distance,
``functional/text/helper.py:333-354``) or buys from third-party C extensions
(pycocotools RLE masks, ``detection/mean_ap.py:127-142``).  The shared library
is compiled with ``g++ -O3`` on first use and cached next to this file; if no
toolchain is available everything silently falls back to Python.
"""

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_SCRATCH = threading.local()


def _build_lib() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and load the shared library; None on any failure."""
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        so_path = os.path.join(_HERE, f"_native_{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, so_path)
            # drop libraries built from older source revisions
            for name in os.listdir(_HERE):
                if name.startswith("_native_") and name.endswith(".so") and name != os.path.basename(so_path):
                    try:
                        os.remove(os.path.join(_HERE, name))
                    except OSError:
                        pass
        lib = ctypes.CDLL(so_path)
        lib.mtpu_edit_distance.restype = ctypes.c_int64
        lib.mtpu_edit_distance.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ]
        lib.mtpu_edit_distance_batch.restype = None
        lib.mtpu_edit_distance_batch.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mtpu_rle_encode.restype = ctypes.c_int64
        lib.mtpu_rle_encode.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.mtpu_rle_encode_batch.restype = ctypes.c_int64
        lib.mtpu_rle_encode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mtpu_rle_decode.restype = None
        lib.mtpu_rle_decode.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ]
        lib.mtpu_rle_area.restype = ctypes.c_int64
        lib.mtpu_rle_area.argtypes = [ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64]
        lib.mtpu_rle_area_batch.restype = None
        lib.mtpu_rle_area_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_double),
        ]
        lib.mtpu_rle_intersection.restype = ctypes.c_int64
        lib.mtpu_rle_intersection.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
        ]
        lib.mtpu_lap_batch.restype = None
        lib.mtpu_lap_batch.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mtpu_coco_match.restype = None
        lib.mtpu_coco_match.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.mtpu_box_iou_blocks.restype = None
        lib.mtpu_box_iou_blocks.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_double),
        ]
        lib.mtpu_rle_iou_blocks.restype = None
        lib.mtpu_rle_iou_blocks.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_double),
        ]
        lib.mtpu_coco_tables.restype = None
        lib.mtpu_coco_tables.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ]
        lib.mtpu_coco_match_blocks.restype = None
        lib.mtpu_coco_match_blocks.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
        ]
        return lib
    except Exception:
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    with _LOCK:
        if not _TRIED:
            _LIB = _build_lib()
            _TRIED = True
    return _LIB


def native_available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# Edit distance
# ---------------------------------------------------------------------------
def _intern(*seqs: Sequence[str]) -> List[np.ndarray]:
    table: dict = {}
    out = []
    for seq in seqs:
        ids = np.empty(len(seq), dtype=np.int64)
        for i, tok in enumerate(seq):
            ids[i] = table.setdefault(tok, len(table))
        out.append(ids)
    return out


def _edit_distance_py(a: np.ndarray, b: np.ndarray) -> int:
    """Two-row DP fallback (vectorized inner loop over numpy)."""
    na, nb = len(a), len(b)
    if na == 0:
        return nb
    if nb == 0:
        return na
    prev = np.arange(nb + 1, dtype=np.int64)
    for i in range(1, na + 1):
        cur = np.empty(nb + 1, dtype=np.int64)
        cur[0] = i
        sub = prev[:-1] + (a[i - 1] != b)
        dele = prev[1:] + 1
        best = np.minimum(sub, dele)
        # insertion column carries a sequential dependency; resolve with a
        # running-min scan: cur[j] = min(best[j-1], cur[j-1]+1)
        run = cur[0]
        for j in range(1, nb + 1):
            run = min(run + 1, best[j - 1])
            cur[j] = run
        prev = cur
    return int(prev[nb])


def edit_distance(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> int:
    """Levenshtein distance between two token sequences (words or chars)."""
    a, b = _intern(pred_tokens, target_tokens)
    lib = get_lib()
    if lib is not None:
        return int(
            lib.mtpu_edit_distance(
                a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(a),
                b.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(b),
            )
        )
    return _edit_distance_py(a, b)


def edit_distance_batch(
    preds: Sequence[Sequence[str]], targets: Sequence[Sequence[str]]
) -> np.ndarray:
    """Per-pair Levenshtein distances in one native call."""
    assert len(preds) == len(targets)
    n = len(preds)
    lib = get_lib()
    if lib is None or n == 0:
        return np.asarray(
            [edit_distance(p, t) for p, t in zip(preds, targets)], dtype=np.int64
        )
    interned = _intern(*preds, *targets)
    a_ids, b_ids = interned[:n], interned[n:]
    a_flat = np.concatenate(a_ids) if a_ids else np.empty(0, np.int64)
    b_flat = np.concatenate(b_ids) if b_ids else np.empty(0, np.int64)
    a_flat = np.ascontiguousarray(a_flat, dtype=np.int64)
    b_flat = np.ascontiguousarray(b_flat, dtype=np.int64)
    a_lens = np.asarray([len(x) for x in a_ids], dtype=np.int64)
    b_lens = np.asarray([len(x) for x in b_ids], dtype=np.int64)
    out = np.empty(n, dtype=np.int64)
    lib.mtpu_edit_distance_batch(
        a_flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        a_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        b_flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        b_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


# ---------------------------------------------------------------------------
# COCO greedy matching
# ---------------------------------------------------------------------------
def coco_match(ious: np.ndarray, gt_ignore: np.ndarray, thresholds: np.ndarray):
    """Greedy COCO matching across all thresholds; None if no native lib.

    Args: ious (n_det, n_gt) float64 (dets score-sorted, gts
    non-ignored-first), gt_ignore (n_gt,) bool, thresholds (T,) float64.
    Returns (det_match (T, n_det) int64, det_ignore (T, n_det) bool,
    gt_matched (T, n_gt) bool).
    """
    lib = get_lib()
    if lib is None:
        return None
    ious = np.ascontiguousarray(ious, dtype=np.float64)
    gt_ignore_u8 = np.ascontiguousarray(gt_ignore, dtype=np.uint8)
    thresholds = np.ascontiguousarray(thresholds, dtype=np.float64)
    n_det, n_gt = ious.shape
    T = len(thresholds)
    det_match = np.empty((T, n_det), dtype=np.int64)
    det_ignore = np.zeros((T, n_det), dtype=np.uint8)
    gt_matched = np.zeros((T, n_gt), dtype=np.uint8)
    lib.mtpu_coco_match(
        ious.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n_det, n_gt,
        gt_ignore_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        thresholds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), T,
        det_match.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        det_ignore.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        gt_matched.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return det_match, det_ignore.astype(bool), gt_matched.astype(bool)


def _i64(x: np.ndarray):
    return np.ascontiguousarray(x, dtype=np.int64)


def box_iou_blocks(dboxes: np.ndarray, nd: np.ndarray, gboxes: np.ndarray, ng: np.ndarray):
    """Pairwise IoU for B independent xyxy blocks in one native call.

    Args: dboxes (sum_nd, 4) and gboxes (sum_ng, 4) float64 concatenated in
    block order; nd/ng (B,) per-block counts.  Returns the flat concatenation
    of row-major (nd[b], ng[b]) blocks, or None if no native lib.
    """
    lib = get_lib()
    if lib is None:
        return None
    nd, ng = _i64(nd), _i64(ng)
    dboxes = np.ascontiguousarray(dboxes, dtype=np.float64)
    gboxes = np.ascontiguousarray(gboxes, dtype=np.float64)
    out = np.empty(int((nd * ng).sum()), dtype=np.float64)
    lib.mtpu_box_iou_blocks(
        dboxes.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        nd.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        gboxes.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ng.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(nd),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out


def rle_iou_blocks(
    druns: np.ndarray, drunlens: np.ndarray, gruns: np.ndarray, grunlens: np.ndarray,
    nd: np.ndarray, ng: np.ndarray,
):
    """Pairwise RLE-mask IoU for B independent blocks in one native call.

    Args: druns/gruns — all masks' uint32 run arrays concatenated in block
    order; drunlens/grunlens — per-mask run counts; nd/ng — masks per block.
    Returns the flat (nd[b], ng[b]) block concatenation, or None.
    """
    lib = get_lib()
    if lib is None:
        return None
    nd, ng = _i64(nd), _i64(ng)
    druns = np.ascontiguousarray(druns, dtype=np.uint32)
    gruns = np.ascontiguousarray(gruns, dtype=np.uint32)
    drunlens, grunlens = _i64(drunlens), _i64(grunlens)
    out = np.empty(int((nd * ng).sum()), dtype=np.float64)
    lib.mtpu_rle_iou_blocks(
        druns.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        drunlens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        gruns.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        grunlens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        nd.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ng.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(nd),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out


def coco_match_blocks(
    ious_flat: np.ndarray, nd: np.ndarray, ng: np.ndarray,
    gt_ignore: np.ndarray, thresholds: np.ndarray,
):
    """Greedy COCO matching for B independent blocks in one native call.

    Args: ious_flat — concatenated row-major (nd[b], ng[b]) blocks; gt_ignore
    — concatenated per-gt flags in block order; thresholds (T,).  Returns
    codes (T, sum_nd) uint8 (0 unmatched / 1 matched counted / 2 matched
    ignored) with block b's columns at its running det offset, or None.
    """
    lib = get_lib()
    if lib is None:
        return None
    nd, ng = _i64(nd), _i64(ng)
    ious_flat = np.ascontiguousarray(ious_flat, dtype=np.float64)
    gt_ignore = np.ascontiguousarray(gt_ignore, dtype=np.uint8)
    thresholds = np.ascontiguousarray(thresholds, dtype=np.float64)
    total_det = int(nd.sum())
    codes = np.empty((len(thresholds), total_det), dtype=np.uint8)
    lib.mtpu_coco_match_blocks(
        ious_flat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        nd.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ng.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(nd),
        gt_ignore.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        thresholds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(thresholds),
        total_det,
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return codes


def coco_tables(
    codes: np.ndarray, cols: np.ndarray, dout: np.ndarray,
    seg_starts: np.ndarray, seg_sizes: np.ndarray,
    npig: np.ndarray, rec_thrs: np.ndarray,
):
    """Per-class-segment precision/recall tables in one native call.

    Args: codes (T, N_full) uint8 raw match-code table; cols — column ids
    selecting and ordering the evaluated detections by (class, score desc);
    dout (N_full,) bool out-of-area flags (original column order);
    seg_starts/seg_sizes (S,) per-class segments as positions into ``cols``;
    npig (S,) counted gts per segment; rec_thrs (R,) ascending recall
    thresholds.  Returns (precision (T, R, S), recall (T, S)) with segments
    of ``npig <= 0`` zero-filled, or None if no native lib.
    """
    lib = get_lib()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    cols = _i64(cols)
    dout = np.ascontiguousarray(dout, dtype=np.uint8)
    seg_starts, seg_sizes = _i64(seg_starts), _i64(seg_sizes)
    npig = np.ascontiguousarray(npig, dtype=np.float64)
    rec_thrs = np.ascontiguousarray(rec_thrs, dtype=np.float64)
    T, N = codes.shape
    S, R = len(seg_starts), len(rec_thrs)
    prec = np.zeros((T, R, S), dtype=np.float64)
    rec = np.zeros((T, S), dtype=np.float64)
    lib.mtpu_coco_tables(
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), N,
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        dout.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        seg_starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        seg_sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        npig.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        rec_thrs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        T, S, R,
        prec.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        rec.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return prec, rec


# ---------------------------------------------------------------------------
# RLE masks (COCO column-major convention)
# ---------------------------------------------------------------------------
def rle_encode_batch(masks: np.ndarray):
    """Encode a stacked (N, H, W) mask tensor in one native call.

    Returns (runs, runcounts): all masks' uncompressed column-major RLE run
    arrays concatenated, plus per-mask run counts — exactly the segm state
    layout of ``MeanAveragePrecision``.  Falls back to per-mask encodes
    without the native lib.
    """
    masks = np.ascontiguousarray(masks, dtype=np.uint8)
    if masks.ndim != 3:
        raise ValueError(f"rle_encode_batch expects (N, H, W), got {masks.shape}")
    n, h, w = masks.shape
    lib = get_lib()
    if lib is None or n == 0:
        rles = [rle_encode(m) for m in masks]
        runs = np.concatenate(rles) if rles else np.zeros(0, np.uint32)
        return runs, np.asarray([len(r) for r in rles], np.int64)
    # worst-case capacity is n*(h*w+1); reuse a growing thread-local scratch
    # so a streaming update loop is not one large allocation per call
    need = n * (h * w + 1)
    runs = getattr(_SCRATCH, "runs", None)
    if runs is None or runs.size < need:
        runs = np.empty(max(need, 1 << 20), dtype=np.uint32)
        _SCRATCH.runs = runs
    runcounts = np.empty(n, dtype=np.int64)
    total = lib.mtpu_rle_encode_batch(
        masks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, h, w,
        runs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        runcounts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return runs[:total].copy(), runcounts



def rle_encode(mask: np.ndarray) -> np.ndarray:
    """Binary HxW mask -> uncompressed RLE counts (column-major, 0-run first)."""
    mask = np.ascontiguousarray(np.asfortranarray(mask.astype(np.uint8)).ravel(order="F"))
    h, w = 0, 0  # only total length matters to the kernel
    lib = get_lib()
    if lib is not None:
        counts = np.empty(mask.size + 1, dtype=np.uint32)
        n_runs = lib.mtpu_rle_encode(
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            mask.size, 1,
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        return counts[:n_runs].copy()
    # python fallback
    flat = mask
    if flat.size == 0:
        return np.asarray([0], dtype=np.uint32)
    change = np.flatnonzero(np.diff(flat)) + 1
    bounds = np.concatenate([[0], change, [flat.size]])
    runs = np.diff(bounds).astype(np.uint32)
    if flat[0] == 1:
        runs = np.concatenate([[np.uint32(0)], runs])
    return runs


def rle_decode(counts: np.ndarray, shape: tuple) -> np.ndarray:
    """Uncompressed RLE counts -> binary mask of `shape` (column-major)."""
    n = int(np.prod(shape))
    counts = np.ascontiguousarray(counts, dtype=np.uint32)
    lib = get_lib()
    if lib is not None:
        flat = np.empty(n, dtype=np.uint8)
        lib.mtpu_rle_decode(
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(counts),
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n,
        )
    else:
        flat = np.zeros(n, dtype=np.uint8)
        pos, v = 0, 0
        for c in counts:
            end = min(pos + int(c), n)
            if v:
                flat[pos:end] = 1
            pos = end
            v = 1 - v
    return flat.reshape(shape, order="F")


def rle_area(counts: np.ndarray) -> int:
    counts = np.ascontiguousarray(counts, dtype=np.uint32)
    lib = get_lib()
    if lib is not None:
        return int(lib.mtpu_rle_area(counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(counts)))
    return int(counts[1::2].sum())


def rle_area_batch(runs: np.ndarray, runcounts: np.ndarray):
    """Per-mask areas over concatenated run arrays; None if no native lib."""
    lib = get_lib()
    if lib is None:
        return None
    runs = np.ascontiguousarray(runs, dtype=np.uint32)
    runcounts = _i64(runcounts)
    out = np.empty(len(runcounts), dtype=np.float64)
    lib.mtpu_rle_area_batch(
        runs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        runcounts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(runcounts),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out


def rle_iou(a: np.ndarray, b: np.ndarray, iscrowd_b: bool = False) -> float:
    """IoU of two RLE masks over the same canvas; crowd GT uses area(a) denom."""
    a = np.ascontiguousarray(a, dtype=np.uint32)
    b = np.ascontiguousarray(b, dtype=np.uint32)
    lib = get_lib()
    if lib is not None:
        inter = int(
            lib.mtpu_rle_intersection(
                a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(a),
                b.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(b),
            )
        )
    else:
        pos_a = np.cumsum(a)
        pos_b = np.cumsum(b)
        n = int(min(pos_a[-1] if len(pos_a) else 0, pos_b[-1] if len(pos_b) else 0))
        ma = rle_decode(a, (n,)) if n else np.zeros(0, np.uint8)
        mb = rle_decode(b, (n,)) if n else np.zeros(0, np.uint8)
        inter = int(np.logical_and(ma, mb).sum())
    area_a, area_b = rle_area(a), rle_area(b)
    denom = area_a if iscrowd_b else (area_a + area_b - inter)
    return inter / denom if denom > 0 else 0.0


# ---------------------------------------------------------------------------
# Linear assignment (Jonker-Volgenant shortest augmenting paths)
# ---------------------------------------------------------------------------
def _lap_py(cost: np.ndarray) -> np.ndarray:
    """Pure-Python JV fallback: min-cost assignment of one (n, n) matrix.

    Same algorithm as the native ``mtpu_lap_batch`` kernel: dual potentials
    u/v plus shortest augmenting paths, O(n^3).
    """
    n = cost.shape[0]
    INF = float("inf")
    u = [0.0] * (n + 1)
    v = [0.0] * (n + 1)
    p = [0] * (n + 1)
    way = [0] * (n + 1)
    for i in range(1, n + 1):
        p[0] = i
        j0 = 0
        minv = [INF] * (n + 1)
        used = [False] * (n + 1)
        while True:
            used[j0] = True
            i0, j1, delta = p[j0], 0, INF
            for j in range(1, n + 1):
                if used[j]:
                    continue
                cur = cost[i0 - 1, j - 1] - u[i0] - v[j]
                if cur < minv[j]:
                    minv[j] = cur
                    way[j] = j0
                if minv[j] < delta:
                    delta = minv[j]
                    j1 = j
            for j in range(n + 1):
                if used[j]:
                    u[p[j]] += delta
                    v[j] -= delta
                else:
                    minv[j] -= delta
            j0 = j1
            if p[j0] == 0:
                break
        while j0:
            j1 = way[j0]
            p[j0] = p[j1]
            j0 = j1
    out = np.empty(n, dtype=np.int64)
    for j in range(1, n + 1):
        if p[j]:
            out[p[j] - 1] = j - 1
    return out


def lap_batch(cost: np.ndarray) -> np.ndarray:
    """Min-cost linear assignment for a batch of square matrices.

    Args: cost (batch, n, n) — ``out[b, i]`` is the column assigned to row i.
    The device path enumerates permutations for small n (audio PIT); this is
    the host path for large n, replacing the reference's scipy
    ``linear_sum_assignment`` dependency (``functional/audio/pit.py:28-49``).
    """
    cost = np.ascontiguousarray(cost, dtype=np.float64)
    if cost.ndim != 3 or cost.shape[1] != cost.shape[2]:
        raise ValueError(f"lap_batch expects (batch, n, n), got {cost.shape}")
    if not np.isfinite(cost).all():
        # NaN would make every dual comparison false and hang the
        # augmenting-path loop (scipy raises on this input too)
        raise ValueError("lap_batch: cost matrix contains non-finite entries")
    batch, n = cost.shape[0], cost.shape[1]
    if n == 0 or batch == 0:
        return np.zeros((batch, n), dtype=np.int64)
    lib = get_lib()
    if lib is not None:
        out = np.empty((batch, n), dtype=np.int64)
        lib.mtpu_lap_batch(
            cost.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            batch, n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return out
    return np.stack([_lap_py(cost[b]) for b in range(batch)])
