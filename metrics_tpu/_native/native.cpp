// metrics_tpu native host kernels.
//
// TPU-native framework design note: the XLA/jit path handles all tensor math;
// these kernels cover the host-orchestrated, genuinely sequential algorithms
// the reference delegates to pure Python (edit distances,
// reference functional/text/helper.py:333-354) or to third-party C extensions
// (pycocotools RLE, reference detection/mean_ap.py:127-142).  Built on demand
// with g++ into a shared library loaded via ctypes; every entry point has a
// pure-Python fallback so the library is optional.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Levenshtein distance over token-id sequences (two-row DP).
int64_t mtpu_edit_distance(const int64_t* a, int64_t na, const int64_t* b, int64_t nb) {
    if (na == 0) return nb;
    if (nb == 0) return na;
    std::vector<int64_t> prev(nb + 1), cur(nb + 1);
    for (int64_t j = 0; j <= nb; ++j) prev[j] = j;
    for (int64_t i = 1; i <= na; ++i) {
        cur[0] = i;
        const int64_t ai = a[i - 1];
        for (int64_t j = 1; j <= nb; ++j) {
            const int64_t sub = prev[j - 1] + (ai == b[j - 1] ? 0 : 1);
            cur[j] = std::min(sub, std::min(prev[j] + 1, cur[j - 1] + 1));
        }
        std::swap(prev, cur);
    }
    return prev[nb];
}

// Batched edit distance: sequences are concatenated in `a`/`b` with per-pair
// lengths; writes one distance per pair into `out`.
void mtpu_edit_distance_batch(const int64_t* a, const int64_t* a_lens,
                              const int64_t* b, const int64_t* b_lens,
                              int64_t n_pairs, int64_t* out) {
    int64_t ao = 0, bo = 0;
    for (int64_t p = 0; p < n_pairs; ++p) {
        out[p] = mtpu_edit_distance(a + ao, a_lens[p], b + bo, b_lens[p]);
        ao += a_lens[p];
        bo += b_lens[p];
    }
}

// COCO-style uncompressed RLE over a column-major binary mask.
// Counts alternate runs of 0s and 1s starting with 0.  Returns the number of
// runs written (capacity must be h*w+1).
int64_t mtpu_rle_encode(const uint8_t* mask, int64_t h, int64_t w, uint32_t* counts) {
    const int64_t n = h * w;
    int64_t n_runs = 0;
    uint8_t prev = 0;
    uint32_t run = 0;
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t v = mask[i];  // caller passes column-major (Fortran) order
        if (v != prev) {
            counts[n_runs++] = run;
            run = 0;
            prev = v;
        }
        ++run;
    }
    counts[n_runs++] = run;
    return n_runs;
}

// Batched RLE encode: n C-contiguous (h, w) masks in one call.  Each mask is
// scanned in column-major (Fortran) order via stride arithmetic — no host-side
// transpose copy.  Runs for all masks are written back to back into
// `runs` (capacity n*(h*w+1)); per-mask run counts go to `runcounts`.
// Returns the total number of runs written.
int64_t mtpu_rle_encode_batch(const uint8_t* masks, int64_t n, int64_t h, int64_t w,
                              uint32_t* runs, int64_t* runcounts) {
    // A column-major scan of a row-major mask is cache-hostile (one byte per
    // cache line).  Instead: consecutive column-major elements are vertical
    // neighbours, so value changes are exactly the row[i] != row[i+1]
    // positions — detected row-major (sequential loads, 8-byte XOR fast
    // skip over equal spans), plus the column-seam comparisons
    // (h-1, j) -> (0, j+1).  Boundary positions are then sorted (masks have
    // few boundaries) and differenced into runs.
    int64_t total = 0;
    std::vector<int64_t> bnd;
    for (int64_t m = 0; m < n; ++m) {
        const uint8_t* M = masks + m * h * w;
        uint32_t* out = runs + total;
        int64_t n_runs = 0;
        if (h * w == 0) {
            out[n_runs++] = 0;
            runcounts[m] = n_runs;
            total += n_runs;
            continue;
        }
        bnd.clear();
        for (int64_t i = 0; i + 1 < h; ++i) {
            const uint8_t* r0 = M + i * w;
            const uint8_t* r1 = r0 + w;
            int64_t j = 0;
            // 64-byte fast path: one branch per cache line of equal bytes
            for (; j + 64 <= w; j += 64) {
                uint64_t acc = 0;
                for (int64_t c = 0; c < 64; c += 8) {
                    uint64_t a, b;
                    std::memcpy(&a, r0 + j + c, 8);
                    std::memcpy(&b, r1 + j + c, 8);
                    acc |= a ^ b;
                }
                if (acc == 0) continue;
                for (int64_t k = j; k < j + 64; ++k)
                    if ((r0[k] != 0) != (r1[k] != 0)) bnd.push_back(k * h + i + 1);
            }
            for (; j + 8 <= w; j += 8) {
                uint64_t a, b;
                std::memcpy(&a, r0 + j, 8);
                std::memcpy(&b, r1 + j, 8);
                if (a == b) continue;
                for (int64_t k = j; k < j + 8; ++k)
                    if ((r0[k] != 0) != (r1[k] != 0)) bnd.push_back(k * h + i + 1);
            }
            for (; j < w; ++j)
                if ((r0[j] != 0) != (r1[j] != 0)) bnd.push_back(j * h + i + 1);
        }
        const uint8_t* last = M + (h - 1) * w;
        for (int64_t j = 0; j + 1 < w; ++j)
            if ((last[j] != 0) != (M[j + 1] != 0)) bnd.push_back((j + 1) * h);
        std::sort(bnd.begin(), bnd.end());
        if (M[0] != 0) out[n_runs++] = 0;  // RLE starts with the zero run
        int64_t prev = 0;
        for (const int64_t p : bnd) {
            out[n_runs++] = (uint32_t)(p - prev);
            prev = p;
        }
        out[n_runs++] = (uint32_t)(h * w - prev);
        runcounts[m] = n_runs;
        total += n_runs;
    }
    return total;
}

void mtpu_rle_decode(const uint32_t* counts, int64_t n_runs, uint8_t* mask, int64_t n) {
    int64_t pos = 0;
    uint8_t v = 0;
    for (int64_t r = 0; r < n_runs && pos < n; ++r) {
        const int64_t end = std::min(pos + (int64_t)counts[r], n);
        if (v) std::memset(mask + pos, 1, end - pos);
        else   std::memset(mask + pos, 0, end - pos);
        pos = end;
        v = 1 - v;
    }
    // zero any canvas tail a truncated run list leaves uncovered
    if (pos < n) std::memset(mask + pos, 0, n - pos);
}

// Pairwise IoU between two RLE mask sets given per-mask areas and
// pre-decoded masks is cheaper done densely; for RLE-native IoU we
// intersect run lists directly (the pycocotools trick) to stay O(runs).
int64_t mtpu_rle_area(const uint32_t* counts, int64_t n_runs) {
    int64_t area = 0;
    for (int64_t r = 1; r < n_runs; r += 2) area += counts[r];
    return area;
}

// Per-mask areas over concatenated run arrays in one call.
void mtpu_rle_area_batch(const uint32_t* runs, const int64_t* runcounts,
                         int64_t n_masks, double* out) {
    int64_t off = 0;
    for (int64_t m = 0; m < n_masks; ++m) {
        out[m] = (double)mtpu_rle_area(runs + off, runcounts[m]);
        off += runcounts[m];
    }
}

// Intersection area of two RLEs over the same canvas.
int64_t mtpu_rle_intersection(const uint32_t* a, int64_t na, const uint32_t* b, int64_t nb) {
    int64_t ia = 0, ib = 0;          // run indices
    int64_t pa = 0, pb = 0;          // absolute end position of current run
    uint8_t va = 0, vb = 0;          // current run values
    int64_t pos = 0, inter = 0;
    pa = (na > 0) ? (int64_t)a[0] : 0;
    pb = (nb > 0) ? (int64_t)b[0] : 0;
    while (ia < na && ib < nb) {
        const int64_t nxt = std::min(pa, pb);
        if (va && vb) inter += nxt - pos;
        pos = nxt;
        if (pa == nxt) { ++ia; if (ia < na) pa += (int64_t)a[ia]; va = 1 - va; }
        if (pb == nxt) { ++ib; if (ib < nb) pb += (int64_t)b[ib]; vb = 1 - vb; }
    }
    return inter;
}

// Greedy COCO detection matching for all IoU thresholds in one call.
// ious is row-major (n_det, n_gt) with detections pre-sorted by score and
// ground truths sorted non-ignored-first; outputs are (T, n_det)/(T, n_gt).
void mtpu_coco_match(const double* ious, int64_t n_det, int64_t n_gt,
                     const uint8_t* gt_ignore, const double* thresholds, int64_t n_thr,
                     int64_t* det_match, uint8_t* det_ignore, uint8_t* gt_matched) {
    for (int64_t ti = 0; ti < n_thr; ++ti) {
        int64_t* dm = det_match + ti * n_det;
        uint8_t* dig = det_ignore + ti * n_det;
        uint8_t* gm = gt_matched + ti * n_gt;
        for (int64_t d = 0; d < n_det; ++d) {
            double best_iou = std::min(thresholds[ti], 1.0 - 1e-10);
            int64_t best_g = -1;
            const double* row = ious + d * n_gt;
            for (int64_t g = 0; g < n_gt; ++g) {
                if (gm[g]) continue;
                // gts sorted non-ignored first: stop at the ignored region
                // once a real match exists
                if (best_g > -1 && !gt_ignore[best_g] && gt_ignore[g]) break;
                if (row[g] < best_iou) continue;
                best_iou = row[g];
                best_g = g;
            }
            dm[d] = best_g;
            dig[d] = (best_g > -1) ? gt_ignore[best_g] : 0;
            if (best_g > -1) gm[best_g] = 1;
        }
    }
}

// Pairwise IoU for independent xyxy box blocks in one call (the per-
// (image,class) IoU blocks of COCO mAP).  dboxes/gboxes are the
// concatenated (sum_nd, 4)/(sum_ng, 4) tables; out receives the
// concatenated row-major nd[b] x ng[b] blocks.
void mtpu_box_iou_blocks(const double* dboxes, const int64_t* nd,
                         const double* gboxes, const int64_t* ng,
                         int64_t n_blocks, double* out) {
    int64_t d_off = 0, g_off = 0, o_off = 0;
    for (int64_t b = 0; b < n_blocks; ++b) {
        const double* D = dboxes + d_off * 4;
        const double* G = gboxes + g_off * 4;
        for (int64_t i = 0; i < nd[b]; ++i) {
            const double dx1 = D[i * 4], dy1 = D[i * 4 + 1];
            const double dx2 = D[i * 4 + 2], dy2 = D[i * 4 + 3];
            const double da = (dx2 - dx1) * (dy2 - dy1);
            double* row = out + o_off + i * ng[b];
            for (int64_t j = 0; j < ng[b]; ++j) {
                const double gx1 = G[j * 4], gy1 = G[j * 4 + 1];
                const double gx2 = G[j * 4 + 2], gy2 = G[j * 4 + 3];
                const double w = std::min(dx2, gx2) - std::max(dx1, gx1);
                const double h = std::min(dy2, gy2) - std::max(dy1, gy1);
                const double inter = (w > 0 && h > 0) ? w * h : 0.0;
                const double ga = (gx2 - gx1) * (gy2 - gy1);
                const double uni = da + ga - inter;
                row[j] = uni > 0 ? inter / std::max(uni, 1e-12) : 0.0;
            }
        }
        d_off += nd[b];
        g_off += ng[b];
        o_off += nd[b] * ng[b];
    }
}

// Pairwise RLE-mask IoU for independent blocks (segm mAP).  druns/gruns are
// every mask's run array concatenated in block order; drunlens/grunlens give
// each mask's run count; nd/ng give the masks per block.  Output layout
// matches mtpu_box_iou_blocks.
void mtpu_rle_iou_blocks(const uint32_t* druns, const int64_t* drunlens,
                         const uint32_t* gruns, const int64_t* grunlens,
                         const int64_t* nd, const int64_t* ng, int64_t n_blocks,
                         double* out) {
    int64_t dmask = 0, gmask = 0, o = 0;
    int64_t droff = 0, groff = 0;
    std::vector<int64_t> d_start, g_start, d_area, g_area;
    for (int64_t b = 0; b < n_blocks; ++b) {
        d_start.assign(nd[b], 0); d_area.assign(nd[b], 0);
        g_start.assign(ng[b], 0); g_area.assign(ng[b], 0);
        for (int64_t i = 0; i < nd[b]; ++i) {
            d_start[i] = droff;
            d_area[i] = mtpu_rle_area(druns + droff, drunlens[dmask + i]);
            droff += drunlens[dmask + i];
        }
        for (int64_t j = 0; j < ng[b]; ++j) {
            g_start[j] = groff;
            g_area[j] = mtpu_rle_area(gruns + groff, grunlens[gmask + j]);
            groff += grunlens[gmask + j];
        }
        for (int64_t i = 0; i < nd[b]; ++i)
            for (int64_t j = 0; j < ng[b]; ++j) {
                const int64_t inter = mtpu_rle_intersection(
                    druns + d_start[i], drunlens[dmask + i],
                    gruns + g_start[j], grunlens[gmask + j]);
                const int64_t uni = d_area[i] + g_area[j] - inter;
                out[o + i * ng[b] + j] = uni > 0 ? (double)inter / (double)uni : 0.0;
            }
        dmask += nd[b];
        gmask += ng[b];
        o += nd[b] * ng[b];
    }
}

// Batched greedy COCO matching over independent (nd[b], ng[b]) IoU blocks in
// ONE call (replaces one ctypes crossing per image x class x area).  Ground
// truths arrive in their block-original order with per-gt ignore flags; each
// block builds its own stable non-ignored-first visiting order.  codes is
// (n_thr, total_det) with block b's det columns at the running det offset:
// 0 = unmatched, 1 = matched to a counted gt, 2 = matched to an ignored gt.
void mtpu_coco_match_blocks(const double* ious, const int64_t* nd, const int64_t* ng,
                            int64_t n_blocks, const uint8_t* gt_ignore,
                            const double* thresholds, int64_t n_thr,
                            int64_t total_det, uint8_t* codes) {
    std::vector<int64_t> order;
    std::vector<uint8_t> gm;
    int64_t iou_off = 0, d_off = 0, g_off = 0;
    for (int64_t b = 0; b < n_blocks; ++b) {
        const int64_t NDb = nd[b], NGb = ng[b];
        const double* I = ious + iou_off;
        const uint8_t* gig = gt_ignore + g_off;
        order.clear();
        for (int64_t g = 0; g < NGb; ++g)
            if (!gig[g]) order.push_back(g);
        const int64_t n_real = (int64_t)order.size();
        for (int64_t g = 0; g < NGb; ++g)
            if (gig[g]) order.push_back(g);
        gm.assign(NGb, 0);
        for (int64_t ti = 0; ti < n_thr; ++ti) {
            std::fill(gm.begin(), gm.end(), 0);
            uint8_t* C = codes + ti * total_det + d_off;
            for (int64_t d = 0; d < NDb; ++d) {
                double best_iou = std::min(thresholds[ti], 1.0 - 1e-10);
                int64_t best = -1;  // position in visiting order
                const double* row = I + d * NGb;
                for (int64_t oi = 0; oi < NGb; ++oi) {
                    const int64_t g = order[oi];
                    if (gm[g]) continue;
                    // once a counted match exists, stop at the ignored region
                    if (best > -1 && best < n_real && oi >= n_real) break;
                    const double v = row[g];
                    if (v < best_iou) continue;
                    best_iou = v;
                    best = oi;
                }
                if (best == -1) {
                    C[d] = 0;
                    continue;
                }
                const int64_t g = order[best];
                gm[g] = 1;
                C[d] = gig[g] ? 2 : 1;
            }
        }
        iou_off += NDb * NGb;
        d_off += NDb;
        g_off += NGb;
    }
}

// COCO precision/recall tables for all class segments of one (area, max_det)
// cell in one call.  codes is the raw (n_thr, n_col_full) uint8 match-code
// table; `cols` (n_cols) selects and orders the columns by (class, score
// desc) — the kernel gathers on the fly, so the caller never materializes
// the reordered table.  Per-class segments live at seg_starts/seg_sizes
// (positions into `cols`); dout marks detections outside the area range
// (not counted as FP), indexed by original column id.  For every segment
// with npig > 0: cumulative TP/FP over score rank, recall at the last rank,
// monotone non-increasing precision envelope, and the R-point interpolation
// at rec_thrs (searchsorted-left semantics, matching pycocotools).
// Outputs: out_prec (n_thr, n_rec, n_seg), out_rec (n_thr, n_seg); segments
// with npig <= 0 are left untouched.
void mtpu_coco_tables(const uint8_t* codes, int64_t n_col_full,
                      const int64_t* cols, const uint8_t* dout,
                      const int64_t* seg_starts, const int64_t* seg_sizes,
                      const double* npig, const double* rec_thrs,
                      int64_t n_thr, int64_t n_seg, int64_t n_rec,
                      double* out_prec, double* out_rec) {
    // Recall/precision only change at TP steps, and searchsorted-left over a
    // step function always lands on a step position (the zero-tp prefix it
    // can land on has pr == 0, never the suffix max), so it suffices to
    // record rc/pr at the steps: O(#matches) float work over an O(#dets)
    // integer scan, outputs identical to the dense formulation.
    int64_t max_n = 0;
    for (int64_t s = 0; s < n_seg; ++s) max_n = std::max(max_n, seg_sizes[s]);
    std::vector<double> rcs(max_n), prs(max_n);
    for (int64_t s = 0; s < n_seg; ++s) {
        if (!(npig[s] > 0)) continue;
        const int64_t start = seg_starts[s], n = seg_sizes[s];
        const int64_t* I = cols + start;
        for (int64_t t = 0; t < n_thr; ++t) {
            const uint8_t* C = codes + t * n_col_full;
            int64_t tp = 0, fp = 0, ns = 0;
            for (int64_t i = 0; i < n; ++i) {
                const uint8_t v = C[I[i]];
                if (v == 1) {
                    ++tp;
                    rcs[ns] = (double)tp / npig[s];
                    prs[ns] = (double)tp / (double)(tp + fp);
                    ++ns;
                } else if (v == 0 && !dout[I[i]]) {
                    ++fp;
                }
            }
            out_rec[t * n_seg + s] = (double)tp / npig[s];
            // monotone non-increasing precision envelope over the steps
            for (int64_t i = ns - 2; i >= 0; --i) prs[i] = std::max(prs[i], prs[i + 1]);
            // rec_thrs ascends: searchsorted-left over all thresholds is one
            // monotone merge, O(#steps + R)
            double* P = out_prec + t * n_rec * n_seg;
            int64_t idx = 0;
            for (int64_t r = 0; r < n_rec; ++r) {
                while (idx < ns && rcs[idx] < rec_thrs[r]) ++idx;
                P[r * n_seg + s] = idx < ns ? prs[idx] : 0.0;
            }
        }
    }
}

// Batched minimum-cost linear assignment (Jonker-Volgenant style shortest
// augmenting paths with dual potentials, O(n^3) per matrix).  The audio PIT
// metric routes large speaker counts here instead of enumerating n!
// permutations (the reference delegates this regime to scipy's
// linear_sum_assignment, functional/audio/pit.py:28-49).
// cost: (batch, n, n) row-major; out_assign[b*n + i] = column chosen for row i.
void mtpu_lap_batch(const double* cost, int64_t batch, int64_t n, int64_t* out_assign) {
    const double INF = 1e300;
    std::vector<double> u(n + 1), v(n + 1), minv(n + 1);
    std::vector<int64_t> p(n + 1), way(n + 1);
    std::vector<uint8_t> used(n + 1);
    for (int64_t b = 0; b < batch; ++b) {
        const double* a = cost + b * n * n;
        std::fill(u.begin(), u.end(), 0.0);
        std::fill(v.begin(), v.end(), 0.0);
        std::fill(p.begin(), p.end(), 0);
        for (int64_t i = 1; i <= n; ++i) {
            p[0] = i;
            int64_t j0 = 0;
            std::fill(minv.begin(), minv.end(), INF);
            std::fill(used.begin(), used.end(), 0);
            do {
                used[j0] = 1;
                const int64_t i0 = p[j0];
                int64_t j1 = 0;
                double delta = INF;
                for (int64_t j = 1; j <= n; ++j) {
                    if (used[j]) continue;
                    const double cur = a[(i0 - 1) * n + (j - 1)] - u[i0] - v[j];
                    if (cur < minv[j]) { minv[j] = cur; way[j] = j0; }
                    if (minv[j] < delta) { delta = minv[j]; j1 = j; }
                }
                for (int64_t j = 0; j <= n; ++j) {
                    if (used[j]) { u[p[j]] += delta; v[j] -= delta; }
                    else minv[j] -= delta;
                }
                j0 = j1;
            } while (p[j0] != 0);
            do { const int64_t j1 = way[j0]; p[j0] = p[j1]; j0 = j1; } while (j0);
        }
        for (int64_t j = 1; j <= n; ++j)
            if (p[j]) out_assign[b * n + (p[j] - 1)] = j - 1;
    }
}

}  // extern "C"
