"""Stream aggregators (reference ``/root/reference/src/torchmetrics/aggregation.py:24-364``).

``Max/Min/Sum/Cat/MeanMetric`` — scalar/tensor loggers with NaN policies.
``ignore``/float-imputation NaN strategies are pure ``jnp.where`` rewrites and
stay on the jit fast path; ``error``/``warn`` need a host readback and force
eager updates.
"""

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class BaseAggregator(Metric):
    """Common scaffolding for the aggregation metrics."""

    is_differentiable = None
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        fn: str,
        default_value: Union[Array, list],
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed = ("error", "warn", "ignore")
        if not (isinstance(nan_strategy, float) or nan_strategy in allowed):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.state_name = state_name
        if nan_strategy in ("error", "warn"):
            self.jit_update = False  # needs a concrete NaN check on host
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn)

    def _cast_and_nan_check_input(self, x: Any, weight: Any = None) -> Any:
        x = jnp.asarray(x, dtype=jnp.float32)
        if weight is not None:
            weight = jnp.broadcast_to(jnp.asarray(weight, dtype=jnp.float32), x.shape)
        if self.nan_strategy in ("error", "warn"):
            if not isinstance(x, jax.core.Tracer) and bool(jnp.any(jnp.isnan(x))):
                if self.nan_strategy == "error":
                    raise RuntimeError("Encountered `nan` values in tensor")
                rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
                keep = ~jnp.isnan(x)
                if weight is not None:
                    weight = weight[keep]
                x = x[keep]
        elif self.nan_strategy == "ignore":
            keep = ~jnp.isnan(x)
            if weight is not None:
                weight = jnp.where(keep, weight, 0.0)
            x = jnp.where(keep, x, self._nan_neutral())
        else:  # float imputation
            x = jnp.where(jnp.isnan(x), jnp.asarray(self.nan_strategy, dtype=x.dtype), x)
        if weight is None:
            return x
        return x, weight

    def _nan_neutral(self) -> float:
        return 0.0

    def update(self, value: Any) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    def compute(self) -> Array:
        return getattr(self, self.state_name)


class MaxMetric(BaseAggregator):
    """Running max (reference ``aggregation.py:95``)."""

    full_state_update = True
    higher_is_better = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.asarray(-jnp.inf), nan_strategy, state_name="max_value", **kwargs)

    def _nan_neutral(self) -> float:
        return float("-inf")

    def update(self, value: Any) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.max_value = jnp.maximum(self.max_value, jnp.max(value))


class MinMetric(BaseAggregator):
    """Running min (reference ``aggregation.py:146``)."""

    full_state_update = True
    higher_is_better = False

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf), nan_strategy, state_name="min_value", **kwargs)

    def _nan_neutral(self) -> float:
        return float("inf")

    def update(self, value: Any) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.min_value = jnp.minimum(self.min_value, jnp.min(value))


class SumMetric(BaseAggregator):
    """Running sum (reference ``aggregation.py:197``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SumMetric
        >>> metric = SumMetric()
        >>> metric.update(1.0)
        >>> metric.update(jnp.asarray([2.0, 3.0]))
        >>> float(metric.compute())
        6.0
    """

    stackable = True  # one zero-default sum state

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, state_name="sum_value", **kwargs)

    def update(self, value: Any) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.sum_value = self.sum_value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate everything (reference ``aggregation.py:246``)."""

    stackable = False  # the concatenation list grows with the stream

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Any) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value.append(jnp.atleast_1d(value))

    def compute(self) -> Array:
        if isinstance(self.value, list) and self.value:
            return dim_zero_cat(self.value)
        return self.value


class MeanMetric(BaseAggregator):
    """Weighted running mean (reference ``aggregation.py:296-364``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanMetric
        >>> metric = MeanMetric()
        >>> metric.update(1.0)
        >>> metric.update(jnp.asarray([2.0, 3.0]))
        >>> float(metric.compute())
        2.0
    """

    stackable = True  # zero-default sum states (value, weight)

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, state_name="mean_value", **kwargs)
        self.add_state("weight", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, value: Any, weight: Any = 1.0) -> None:
        out = self._cast_and_nan_check_input(value, weight)
        value, weight = out
        if value.size == 0:
            return
        self.mean_value = self.mean_value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        return self.mean_value / self.weight
