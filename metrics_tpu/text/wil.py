"""WordInfoLost module metric (reference ``text/wil.py:23-98``)."""

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wil import _wil_compute, _wil_update
from metrics_tpu.metric import Metric

Array = jax.Array


class WordInfoLost(Metric):
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jit_update_default = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _wil_update(preds, target)
        self._host_accumulate(errors=errors, target_total=target_total, preds_total=preds_total)

    def compute(self) -> Array:
        return _wil_compute(self.errors, self.target_total, self.preds_total)
