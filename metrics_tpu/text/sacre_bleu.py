"""SacreBLEUScore module metric (reference ``text/sacre_bleu.py:32-117``)."""

from typing import Any, Optional, Sequence

from metrics_tpu.functional.text.sacre_bleu import _SacreBLEUTokenizer
from metrics_tpu.text.bleu import BLEUScore


class SacreBLEUScore(BLEUScore):
    """BLEU over a standard WMT tokenizer (subclasses the BLEU count engine)."""

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        self.tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)

    def _tokenizer(self):
        return self.tokenizer
