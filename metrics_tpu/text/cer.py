"""CharErrorRate module metric (reference ``text/cer.py:24-98``)."""

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.cer import _cer_compute, _cer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class CharErrorRate(Metric):
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jit_update_default = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _cer_update(preds, target)
        self._host_accumulate(errors=errors, total=total)

    def compute(self) -> Array:
        return _cer_compute(self.errors, self.total)
