"""TranslationEditRate module metric (reference ``text/ter.py:24-109``)."""

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.ter import _TercomTokenizer, _ter_compute, _ter_update
from metrics_tpu.metric import Metric

Array = jax.Array


class TranslationEditRate(Metric):
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jit_update_default = False

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score
        self.add_state("total_num_edits", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_tgt_length", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", default=[], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> None:
        scores = [] if self.return_sentence_level_score else None
        num_edits, tgt_length = _ter_update(preds, target, self.tokenizer, scores)
        self._host_accumulate(total_num_edits=num_edits, total_tgt_length=tgt_length)
        if self.return_sentence_level_score:
            self.sentence_ter.append(jnp.asarray(scores, jnp.float32))

    def compute(self) -> Union[Array, tuple]:
        score = _ter_compute(self.total_num_edits, self.total_tgt_length)
        if self.return_sentence_level_score:
            return score, jnp.concatenate([jnp.atleast_1d(s) for s in self.sentence_ter])
        return score
