"""ExtendedEditDistance module metric (reference ``text/eed.py:24-102``)."""

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.eed import _eed_compute, _eed_update
from metrics_tpu.metric import Metric

Array = jax.Array


class ExtendedEditDistance(Metric):
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jit_update_default = False

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion
        self.add_state("score_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("score_count", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_eed", default=[], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> None:
        scores = [] if self.return_sentence_level_score else None
        total, count = _eed_update(
            preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion, scores
        )
        self._host_accumulate(score_sum=total, score_count=count)
        if self.return_sentence_level_score:
            self.sentence_eed.append(jnp.asarray(scores, jnp.float32))

    def compute(self) -> Union[Array, tuple]:
        score = _eed_compute(self.score_sum, self.score_count)
        if self.return_sentence_level_score:
            return score, jnp.concatenate([jnp.atleast_1d(s) for s in self.sentence_eed])
        return score
