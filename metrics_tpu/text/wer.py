"""WordErrorRate module metric (reference ``text/wer.py:23-95``)."""

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wer import _wer_compute, _wer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class WordErrorRate(Metric):
    """Streaming word error rate over string batches.

    String inputs are host-side: update folds edit distances into scalar
    device states (sum-reducible), so distributed sync stays tensor-only.
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jit_update_default = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _wer_update(preds, target)
        self._host_accumulate(errors=errors, total=total)

    def compute(self) -> Array:
        return _wer_compute(self.errors, self.total)
