"""BLEUScore module metric (reference ``text/bleu.py:28-120``)."""

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.bleu import (
    _bleu_normalize_inputs,
    _bleu_score_compute,
    _bleu_score_update,
    _tokenize_fn,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class BLEUScore(Metric):
    """Streaming corpus BLEU with fixed-shape ``(n_gram,)`` count states."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    jit_update_default = False

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        _, _, self.weights = _bleu_normalize_inputs([], [], n_gram, weights)
        self.add_state("preds_len", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        preds_, target_, _ = _bleu_normalize_inputs(preds, target, self.n_gram, None)
        numerator, denominator, preds_len, target_len = _bleu_score_update(
            preds_, target_, self.n_gram, self._tokenizer()
        )
        self._host_accumulate(
            preds_len=preds_len, target_len=target_len,
            numerator=numerator, denominator=denominator,
        )

    def _tokenizer(self):
        return _tokenize_fn

    def compute(self) -> Array:
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator,
            self.n_gram, self.weights, self.smooth,
        )
