"""WordInfoPreserved module metric (reference ``text/wip.py:23-97``)."""

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wip import _wip_compute, _wip_update
from metrics_tpu.metric import Metric

Array = jax.Array


class WordInfoPreserved(Metric):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    jit_update_default = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _wip_update(preds, target)
        self._host_accumulate(errors=errors, target_total=target_total, preds_total=preds_total)

    def compute(self) -> Array:
        return _wip_compute(self.errors, self.target_total, self.preds_total)
