"""MatchErrorRate module metric (reference ``text/mer.py:23-95``)."""

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.mer import _mer_compute, _mer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class MatchErrorRate(Metric):
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jit_update_default = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _mer_update(preds, target)
        self._host_accumulate(errors=errors, total=total)

    def compute(self) -> Array:
        return _mer_compute(self.errors, self.total)
