"""BERTScore module metric (reference ``text/bert.py:41-225``).

State design (reference ``text/bert.py:170-203``): update tokenizes strings to
**fixed-width int tensors** so the distributed sync is a tensor all-gather,
never a string exchange.  compute() embeds the gathered corpus and runs the
vmapped greedy-matching kernel.
"""

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.bert import (
    _apply_idf,
    _default_tokenize,
    _idf_weights,
    _load_flax_model,
    _model_forward,
    _run_matching,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class BERTScore(Metric):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    jit_update_default = False
    jit_compute_default = False

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        max_length: int = 128,
        batch_size: int = 64,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_values: Optional[Dict[str, float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if model is None:
            if model_name_or_path is None:
                raise ValueError(
                    "Either `model_name_or_path` or a `model` + `user_tokenizer` must be provided."
                )
            user_tokenizer, model = _load_flax_model(model_name_or_path)
        if user_tokenizer is None:
            raise ValueError("`user_tokenizer` is required when passing an own model.")
        self.model = model
        self.tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self.num_layers = num_layers
        self.all_layers = all_layers
        self.idf = idf
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_hash = return_hash
        self.model_name_or_path = model_name_or_path
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_values = baseline_values
        self.add_state("preds_input_ids", default=[], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", default=[], dist_reduce_fx="cat")
        self.add_state("target_input_ids", default=[], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", default=[], dist_reduce_fx="cat")
        # eager-encode cache: embeddings for a PREFIX of the stored token
        # batches, launched asynchronously during update so the device
        # encoder overlaps host-side tokenization (the reference embeds the
        # whole corpus inside compute, text/bert.py:205-225).  Purely a
        # derived cache: validity is checked by object identity against the
        # live state lists, so any state swap (sync gather, load_state_dict,
        # forward's state juggling) falls back to a full compute-time encode.
        self._enc_src: List[Array] = []  # state arrays the cache covers
        self._enc_cache: Dict[str, List[Array]] = {"p": [], "t": []}
        self._in_forward_batch = False
        self.profile_compute = False
        self.last_compute_breakdown: Dict[str, float] = {}

    def _invalidate_encoder_cache(self) -> None:
        self._enc_src = []
        self._enc_cache = {"p": [], "t": []}

    def reset(self) -> None:
        self._invalidate_encoder_cache()
        super().reset()

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self._invalidate_encoder_cache()
        super().load_state_dict(state_dict)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """forward() juggles state (swap to batch, compute, merge back), so
        the eager-encode cache is suspended for the batch update (encoding
        the batch would be thrown away with the swapped state) and dropped
        afterwards (the merged lists no longer match the cached prefix)."""
        self._in_forward_batch = True
        try:
            return super().forward(*args, **kwargs)
        finally:
            self._in_forward_batch = False
            self._invalidate_encoder_cache()

    def load_state_pytree(self, tree: Dict[str, Any]) -> None:
        self._invalidate_encoder_cache()
        super().load_state_pytree(tree)

    def _cache_is_prefix(self) -> bool:
        stored = self.preds_input_ids
        src = self._enc_src
        return len(src) <= len(stored) and all(a is b for a, b in zip(src, stored))

    def _encode_block(self, ids_list, mask_list, side: str) -> None:
        ids = ids_list[0] if len(ids_list) == 1 else jnp.concatenate(ids_list, axis=0)
        mask = mask_list[0] if len(mask_list) == 1 else jnp.concatenate(mask_list, axis=0)
        emb = _model_forward(self.model, ids, mask, self.num_layers, self.all_layers, self.batch_size)
        self._enc_cache[side].append(emb)

    def _drain_pending_encodes(self) -> None:
        """Encode every stored batch beyond the cached prefix once at least
        ``batch_size`` sentences are pending.  Launches are async: update
        returns while the encoder chunks queue behind earlier work."""
        start = len(self._enc_src)
        pend = self.preds_input_ids[start:]
        if sum(int(x.shape[0]) for x in pend) < max(1, self.batch_size):
            return
        self._encode_block(pend, self.preds_attention_mask[start:], "p")
        self._encode_block(self.target_input_ids[start:], self.target_attention_mask[start:], "t")
        self._enc_src.extend(pend)

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        preds_l = [preds] if isinstance(preds, str) else list(preds)
        target_l = [target] if isinstance(target, str) else list(target)
        if len(preds_l) != len(target_l):
            raise ValueError("Number of predicted and reference sentences must match.")
        p_tok = _default_tokenize(preds_l, self.tokenizer, self.max_length)
        t_tok = _default_tokenize(target_l, self.tokenizer, self.max_length)
        self.preds_input_ids.append(jnp.asarray(p_tok["input_ids"]))
        self.preds_attention_mask.append(jnp.asarray(p_tok["attention_mask"]))
        self.target_input_ids.append(jnp.asarray(t_tok["input_ids"]))
        self.target_attention_mask.append(jnp.asarray(t_tok["attention_mask"]))
        if self.user_forward_fn is None and not self._in_forward_batch:
            if self._cache_is_prefix():
                self._drain_pending_encodes()
            else:
                # state lists were swapped under us (sync gather, manual
                # surgery): drop the stale device embeddings; the cache
                # restarts from the current lists on the next update
                self._invalidate_encoder_cache()

    def compute(self) -> Dict[str, List[float]]:
        # token states stay ON DEVICE through the encoder: round-tripping
        # them through numpy pays a d2h fetch plus one h2d per encoder chunk
        # (seconds over a remote-TPU tunnel); only the idf path needs host
        # token ids, and fetches them just then.
        #
        # ``self.profile_compute = True`` inserts block_until_ready barriers
        # between phases and records wall times in
        # ``last_compute_breakdown`` — off by default because the barriers
        # serialize work the async dispatch would otherwise overlap.  (An
        # attribute rather than a kwarg: the Metric base wraps ``compute``
        # and calls the implementation with no arguments.)
        import time as _time

        profile = self.profile_compute
        bd: Dict[str, float] = {}

        def _tick(key, value, *barrier):
            if profile:
                for b in barrier:
                    jax.block_until_ready(b)
                bd[key] = round(_time.perf_counter() - value, 4)
            return _time.perf_counter()

        t0 = _time.perf_counter()
        p_ids = jnp.concatenate(self.preds_input_ids, axis=0)
        p_mask = jnp.concatenate(self.preds_attention_mask, axis=0)
        t_ids = jnp.concatenate(self.target_input_ids, axis=0)
        t_mask = jnp.concatenate(self.target_attention_mask, axis=0)
        t0 = _tick("concat_secs", t0, p_ids, p_mask, t_ids, t_mask)

        if self.user_forward_fn is not None:
            p_emb = self.user_forward_fn(self.model, p_ids, p_mask)
            t_emb = self.user_forward_fn(self.model, t_ids, t_mask)
        elif self._cache_is_prefix() and self._enc_src:
            # eager cache covers a prefix of the stored batches (async
            # launches already queued during update); encode only the tail
            start = len(self._enc_src)
            tail = self.preds_input_ids[start:]
            if tail:
                self._encode_block(tail, self.preds_attention_mask[start:], "p")
                self._encode_block(self.target_input_ids[start:], self.target_attention_mask[start:], "t")
                self._enc_src.extend(tail)
            p_chunks, t_chunks = self._enc_cache["p"], self._enc_cache["t"]
            p_emb = p_chunks[0] if len(p_chunks) == 1 else jnp.concatenate(p_chunks, axis=-3)
            t_emb = t_chunks[0] if len(t_chunks) == 1 else jnp.concatenate(t_chunks, axis=-3)
            bd["encoder_cached_chunks"] = len(p_chunks)
        else:
            p_emb = _model_forward(self.model, p_ids, p_mask, self.num_layers, self.all_layers, self.batch_size)
            t_emb = _model_forward(self.model, t_ids, t_mask, self.num_layers, self.all_layers, self.batch_size)
        t0 = _tick("encoder_secs", t0, p_emb, t_emb)

        if self.idf:
            p_ids_np, p_mask_np = np.asarray(p_ids), np.asarray(p_mask)
            t_ids_np, t_mask_np = np.asarray(t_ids), np.asarray(t_mask)
            weights = _idf_weights(t_ids_np, t_mask_np, t_ids_np.shape[0])
            pw = _apply_idf(p_ids_np, p_mask_np, weights)
            tw = _apply_idf(t_ids_np, t_mask_np, weights)
        else:
            pw = jnp.ones(p_ids.shape, dtype=jnp.float32)
            tw = jnp.ones(t_ids.shape, dtype=jnp.float32)
        t0 = _tick("idf_secs", t0)

        out = _run_matching(
            # matching always runs f32: a bf16 user model (MXU-rate encoding)
            # still gets f32 cosine similarities and score accumulation
            jnp.asarray(p_emb, jnp.float32), jnp.asarray(p_mask, jnp.float32),
            jnp.asarray(t_emb, jnp.float32), jnp.asarray(t_mask, jnp.float32),
            jnp.asarray(pw, jnp.float32), jnp.asarray(tw, jnp.float32),
        )
        if self.rescale_with_baseline:
            if self.baseline_values is None:
                raise ValueError("`rescale_with_baseline` needs `baseline_values` in offline builds.")
            out = {k: (v - self.baseline_values[k]) / (1.0 - self.baseline_values[k]) for k, v in out.items()}
        t0 = _tick("matching_secs", t0, out)
        # ONE stacked device->host fetch for all three outputs (per-key
        # fetches pay one transfer round trip each over a remote device)
        keys = list(out)
        stacked = np.asarray(jnp.stack([jnp.asarray(out[k]) for k in keys]))
        result = {k: stacked[i].tolist() for i, k in enumerate(keys)}
        _tick("fetch_secs", t0)
        if profile:
            self.last_compute_breakdown = bd
        if self.return_hash:
            result["hash"] = f"metrics_tpu-bert_score-{self.model_name_or_path or 'user-model'}"
        return result
