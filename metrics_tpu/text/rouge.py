"""ROUGEScore module metric (reference ``text/rouge.py:31-154``)."""

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    ALLOWED_ROUGE_KEYS,
    _make_stemmer,
    _rouge_score_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class ROUGEScore(Metric):
    """Streaming ROUGE with per-(key, stat) sum states and a shared count.

    The reference appends per-sentence scores to list states; averaging on the
    fly keeps every state a sum-reducible scalar.

    Example:
        >>> from metrics_tpu import ROUGEScore
        >>> preds = ['the cat sat on the mat']
        >>> target = ['a cat sat on the mat']
        >>> metric = ROUGEScore(rouge_keys=('rouge1',))
        >>> metric.update(preds, target)
        >>> out = metric.compute()
        >>> round(float(out['rouge1_fmeasure']), 6)
        0.833333
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    jit_update_default = False

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if isinstance(rouge_keys, str):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS)}")
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )
        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[k] for k in rouge_keys]
        self.stemmer = _make_stemmer() if use_stemmer else None
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate
        for key in self.rouge_keys_values:
            for stat in ("fmeasure", "precision", "recall"):
                self.add_state(f"rouge{key}_{stat}_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    ) -> None:
        if isinstance(target, list) and all(isinstance(t, str) for t in target):
            target = [target] if isinstance(preds, str) else [[t] for t in target]
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]
        stats = _rouge_score_update(
            preds, target, self.rouge_keys_values, self.accumulate,
            self.stemmer, self.normalizer, self.tokenizer,
        )
        # host-side lazy accumulation (base ``_host_accumulate``): per-update
        # eager adds on 0-d device arrays cost one dispatch per (key, stat)
        # per call — thousands of round trips over a remote-TPU stream
        inc = {}
        n = 0
        for key, per_stat in stats.items():
            for stat, (total, count) in per_stat.items():
                inc[f"rouge{key}_{stat}_sum"] = total
                n = count
        inc["total"] = n
        self._host_accumulate(**inc)

    def compute(self) -> Dict[str, Array]:
        denom = jnp.maximum(self.total, 1.0)
        out = {}
        for key in self.rouge_keys_values:
            for stat in ("fmeasure", "precision", "recall"):
                out[f"rouge{key}_{stat}"] = self._state[f"rouge{key}_{stat}_sum"] / denom
        return out
