"""CHRFScore module metric (reference ``text/chrf.py:46-188``)."""

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.chrf import _chrf_score_compute, _chrf_score_update
from metrics_tpu.metric import Metric

Array = jax.Array


class CHRFScore(Metric):
    """Streaming corpus chrF/chrF++ with per-order array states."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    jit_update_default = False

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)
        self.add_state("preds_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("preds_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("target_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("target_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("matching_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("matching_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf_score", default=[], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> None:
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [[t] if isinstance(t, str) else list(t) for t in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
        scores = [] if self.return_sentence_level_score else None
        p_char, p_word, t_char, t_word, m_char, m_word = _chrf_score_update(
            preds_, target_, self.n_char_order, self.n_word_order,
            self.beta, self.lowercase, self.whitespace, scores,
        )
        self._host_accumulate(
            preds_char=p_char, preds_word=p_word,
            target_char=t_char, target_word=t_word,
            matching_char=m_char, matching_word=m_word,
        )
        if self.return_sentence_level_score:
            self.sentence_chrf_score.append(jnp.asarray(scores, jnp.float32))

    def compute(self) -> Union[Array, tuple]:
        score = _chrf_score_compute(
            self.preds_char, self.preds_word,
            self.target_char, self.target_word,
            self.matching_char, self.matching_word,
            self.n_order, self.beta,
        )
        if self.return_sentence_level_score:
            return score, jnp.concatenate([jnp.atleast_1d(s) for s in self.sentence_chrf_score])
        return score
