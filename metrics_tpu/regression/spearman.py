"""SpearmanCorrCoef module metric (reference ``regression/spearman.py:53-80``)."""

from typing import Any

import jax

from metrics_tpu.functional.regression.spearman import (
    _spearman_corrcoef_compute,
    _spearman_corrcoef_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class SpearmanCorrCoef(Metric):
    """Rank correlation needs the full sample — buffered device states, gather-synced.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrCoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0, 4.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0, 1.0])
        >>> metric = SpearmanCorrCoef()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.7
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    stackable = False  # buffer states (preds/target) grow with the stream

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_buffer_state("preds")
        self.add_buffer_state("target")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _spearman_corrcoef_update(preds, target)
        self._buffer_append("preds", preds)
        self._buffer_append("target", target)

    def compute(self) -> Array:
        preds = self.buffer_values("preds")
        target = self.buffer_values("target")
        return _spearman_corrcoef_compute(preds, target)
