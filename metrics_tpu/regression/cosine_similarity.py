"""CosineSimilarity module metric (reference ``regression/cosine_similarity.py:60-90``)."""

from typing import Any, Optional

import jax

from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class CosineSimilarity(Metric):
    """Holds the raw (N,D) batches (buffered device state, gather-synced)."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    stackable = False  # buffer states (preds/target) grow with the stream

    def __init__(self, reduction: str = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_buffer_state("preds")
        self.add_buffer_state("target")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _cosine_similarity_update(preds, target)
        self._buffer_append("preds", preds)
        self._buffer_append("target", target)

    def compute(self) -> Array:
        preds = self.buffer_values("preds")
        target = self.buffer_values("target")
        return _cosine_similarity_compute(preds, target, self.reduction)
