"""PearsonCorrCoef module metric (reference ``regression/pearson.py:23-66,66-130``).

The six running statistics cannot be merged independently (the variance merge
needs both means), so sync uses plain all-gather (``dist_reduce_fx=None``) and
``compute`` folds the per-device rows with the parallel-variance combination
rule — a jit-friendly ``lax``-free loop over the static device count.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.pearson import (
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, Array, Array, Array]:
    """Merge per-device (mean, var, cov) rows via Chan's parallel algorithm."""
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, means_x.shape[0]):
        mx2, my2, vx2, vy2, cxy2, n2 = means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]
        nb = n1 + n2
        mean_x = (n1 * mx1 + n2 * mx2) / nb
        mean_y = (n1 * my1 + n2 * my2) / nb
        delta_x1, delta_x2 = mx1 - mean_x, mx2 - mean_x
        delta_y1, delta_y2 = my1 - mean_y, my2 - mean_y
        var_x = vx1 + vx2 + n1 * delta_x1 * delta_x1 + n2 * delta_x2 * delta_x2
        var_y = vy1 + vy2 + n1 * delta_y1 * delta_y1 + n2 * delta_y2 * delta_y2
        corr_xy = cxy1 + cxy2 + n1 * delta_x1 * delta_y1 + n2 * delta_x2 * delta_y2
        mx1, my1, vx1, vy1, cxy1, n1 = mean_x, mean_y, var_x, var_y, corr_xy, nb
    return vx1, vy1, cxy1, n1


class PearsonCorrCoef(Metric):
    """``PearsonCorrCoef`` module metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrCoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> metric = PearsonCorrCoef()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 6)
        0.98487
    """

    stackable = True  # fixed-shape Welford accumulators; streams stack independently
    is_differentiable = True
    higher_is_better = None
    full_state_update = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        zero = jnp.zeros((1,), dtype=jnp.float32)
        self.add_state("mean_x", default=zero, dist_reduce_fx=None)
        self.add_state("mean_y", default=zero, dist_reduce_fx=None)
        self.add_state("var_x", default=zero, dist_reduce_fx=None)
        self.add_state("var_y", default=zero, dist_reduce_fx=None)
        self.add_state("corr_xy", default=zero, dist_reduce_fx=None)
        self.add_state("n_total", default=zero, dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        mean_x, mean_y, var_x, var_y, corr_xy, n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
        )
        self.mean_x, self.mean_y = mean_x, mean_y
        self.var_x, self.var_y = var_x, var_y
        self.corr_xy, self.n_total = corr_xy, n_total

    def compute(self) -> Array:
        if self.mean_x.shape[0] > 1:  # post-sync: one row per device
            var_x, var_y, corr_xy, n_total = _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            var_x, var_y, corr_xy, n_total = self.var_x, self.var_y, self.corr_xy, self.n_total
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)
