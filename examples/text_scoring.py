"""Example: corpus-level MT/summarization scoring with BLEU, chrF, TER and
ROUGE (reference ``examples/rouge_score-own_normalizer.py`` analog)."""

from metrics_tpu.text import BLEUScore, CHRFScore, ROUGEScore, TranslationEditRate


def main() -> None:
    hypotheses = [
        "the cat is on the mat",
        "there is a dog in the garden",
    ]
    references = [
        ["a cat is on the mat", "the cat sits on the mat"],
        ["a dog is in the garden"],
    ]

    bleu = BLEUScore()
    chrf = CHRFScore()
    ter = TranslationEditRate()
    bleu.update(hypotheses, references)
    chrf.update(hypotheses, references)
    ter.update(hypotheses, references)
    print(f"BLEU: {float(bleu.compute()):.4f}")
    print(f"chrF++: {float(chrf.compute()):.4f}")
    print(f"TER: {float(ter.compute()):.4f}")

    rouge = ROUGEScore(rouge_keys=("rouge1", "rougeL"))
    rouge.update("the quick brown fox", "a quick brown dog")
    for name, value in rouge.compute().items():
        print(f"{name}: {float(value):.4f}")


if __name__ == "__main__":
    main()
