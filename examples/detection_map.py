"""Example: COCO-protocol mAP over streaming detection batches
(reference ``examples/detection_map.py`` analog)."""

import numpy as np

from metrics_tpu.detection import MeanAveragePrecision


def main() -> None:
    metric = MeanAveragePrecision(box_format="xyxy", class_metrics=True)
    rng = np.random.default_rng(0)
    for _step in range(4):
        preds, targets = [], []
        for _img in range(8):
            n = int(rng.integers(1, 6))
            gt = np.sort(rng.random((n, 2, 2)) * 300, axis=1).reshape(n, 4)
            noisy = gt + rng.normal(scale=3.0, size=gt.shape)
            labels = rng.integers(0, 3, n)
            preds.append(dict(boxes=noisy, scores=rng.random(n), labels=labels))
            targets.append(dict(boxes=gt, labels=labels))
        metric.update(preds, targets)
    result = metric.compute()
    for key in ("map", "map_50", "map_75", "mar_100"):
        print(f"{key}: {float(result[key]):.4f}")
    print("per-class AP:", np.asarray(result["map_per_class"]).round(4))


if __name__ == "__main__":
    main()
