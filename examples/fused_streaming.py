"""Example: the three single-dispatch streaming shapes.

1. ``metric(batch)`` — forward: batch value + accumulation, fused into one
   compiled program with donated state buffers.
2. ``metric.update_batched(stack)`` — a whole stacked stream folded through
   one ``lax.scan`` program.
3. ``BootStrapper(..., "multinomial")`` — every bootstrap replica in one
   vmapped program.

Run anywhere: ``JAX_PLATFORMS=cpu python examples/fused_streaming.py``
"""

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import BootStrapper
from metrics_tpu.classification import Accuracy


def main() -> None:
    rng = np.random.default_rng(0)
    n_batches, batch, classes = 32, 512, 10
    preds = jnp.asarray(rng.random((n_batches, batch, classes), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, classes, size=(n_batches, batch)))

    # 1. training-loop shape: per-step batch value, one dispatch per step
    metric = Accuracy(num_classes=classes, validate_args=False)
    for i in range(n_batches):
        batch_acc = metric(preds[i], target[i])
    print(f"last-batch acc {float(batch_acc):.4f}  epoch acc {float(metric.compute()):.4f}")

    # 2. stacked-stream shape: the whole epoch in ONE dispatch
    fused = Accuracy(num_classes=classes, validate_args=False)
    fused.update_batched(preds, target)
    assert np.isclose(float(fused.compute()), float(metric.compute()))
    print(f"fused epoch acc  {float(fused.compute()):.4f}  (update_batched == loop)")

    # 3. bootstrap confidence band: all replicas in one vmapped program
    boot = BootStrapper(
        Accuracy(num_classes=classes, validate_args=False),
        num_bootstraps=50,
        sampling_strategy="multinomial",
        seed=1,
    )
    for i in range(n_batches):
        boot.update(preds[i], target[i])
    out = boot.compute()
    print(f"bootstrap acc    {float(out['mean']):.4f} +/- {float(out['std']):.4f}")

    jax.block_until_ready(out["mean"])


if __name__ == "__main__":
    main()
