"""Example: the four single-dispatch streaming shapes.

1. ``metric.update(batch)`` — the reference-shaped eager loop; updates
   accumulate by default (``lazy_updates=64``) and flush through one
   ``lax.scan`` dispatch, so the loop does not pay one device dispatch per
   step.  Results are identical to immediate updates.
2. ``metric(batch)`` — forward: batch value + accumulation, fused into one
   compiled program with donated state buffers.
3. ``metric.update_batched(stack)`` — a whole stacked stream folded through
   one ``lax.scan`` program explicitly.
4. ``BootStrapper`` — every bootstrap replica in one vmapped program, for
   BOTH poisson (default) and multinomial resampling.

Run anywhere: ``JAX_PLATFORMS=cpu python examples/fused_streaming.py``
"""

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import BootStrapper
from metrics_tpu.classification import Accuracy


def main() -> None:
    rng = np.random.default_rng(0)
    n_batches, batch, classes = 32, 512, 10
    preds = jnp.asarray(rng.random((n_batches, batch, classes), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, classes, size=(n_batches, batch)))

    # 1. migrated-user shape: plain update() per step — lazily accumulated
    #    and flushed as one scan dispatch per 64 batches (the default)
    lazy = Accuracy(num_classes=classes, validate_args=False)
    for i in range(n_batches):
        lazy.update(preds[i], target[i])
    print(f"lazy loop epoch acc {float(lazy.compute()):.4f}  (dispatches ~ n/64)")

    # 2. training-loop shape: per-step batch value, one dispatch per step
    metric = Accuracy(num_classes=classes, validate_args=False)
    for i in range(n_batches):
        batch_acc = metric(preds[i], target[i])
    print(f"last-batch acc {float(batch_acc):.4f}  epoch acc {float(metric.compute()):.4f}")

    # 3. stacked-stream shape: the whole epoch in ONE explicit dispatch
    fused = Accuracy(num_classes=classes, validate_args=False)
    fused.update_batched(preds, target)
    assert np.isclose(float(fused.compute()), float(metric.compute()))
    assert np.isclose(float(fused.compute()), float(lazy.compute()))
    print(f"fused epoch acc  {float(fused.compute()):.4f}  (update_batched == loop)")

    # 4. bootstrap confidence band: all replicas in one vmapped program
    #    (poisson — the default — uses fixed-capacity resamples; multinomial
    #    shown here)
    boot = BootStrapper(
        Accuracy(num_classes=classes, validate_args=False),
        num_bootstraps=50,
        sampling_strategy="multinomial",
        seed=1,
    )
    for i in range(n_batches):
        boot.update(preds[i], target[i])
    out = boot.compute()
    print(f"bootstrap acc    {float(out['mean']):.4f} +/- {float(out['std']):.4f}")

    jax.block_until_ready(out["mean"])


if __name__ == "__main__":
    main()
