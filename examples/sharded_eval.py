"""Example: data-parallel metric evaluation over a device mesh.

Run with real TPU chips, or simulate locally:
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu python examples/sharded_eval.py``
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy


def main() -> None:
    n_dev = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    metric = Accuracy(num_classes=10, validate_args=False)

    rng = np.random.default_rng(0)
    batch = 64 * n_dev
    preds = jnp.asarray(rng.random((batch, 10), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, 10, size=(batch,)))

    def eval_step(p_shard, t_shard):
        state = metric.init_state()
        state = metric.apply_update(state, p_shard, t_shard)
        # psum over the mesh: every device returns the global value
        return jnp.asarray(metric.apply_compute(state, axis_name="data"))[None]

    fn = jax.jit(
        jax.shard_map(eval_step, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"))
    )
    values = np.asarray(fn(preds, target))
    print(f"devices: {n_dev}, per-device global accuracy: {values.ravel()}")
    assert np.allclose(values, values[0])


if __name__ == "__main__":
    main()
