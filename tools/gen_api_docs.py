"""Generate the per-metric API reference from live docstrings.

The upstream reference (TorchMetrics, ``/root/reference/docs/source/pages/``)
ships ~110 hand-written rst pages, one per metric; here the equivalent
surface is rendered mechanically from each class's signature and docstring
so it can never drift from the code:

    python -m tools.gen_api_docs        # rewrites docs/api/*.md

Run it after adding or changing metrics; ``tests/test_api_docs.py`` asserts
the pages cover every exported module class.
"""

import importlib
import inspect
import os

DOMAINS = [
    ("classification", "Classification"),
    ("regression", "Regression"),
    ("image", "Image"),
    ("text", "Text"),
    ("audio", "Audio"),
    ("retrieval", "Retrieval"),
    ("detection", "Detection"),
    ("wrappers", "Wrappers"),
    ("aggregation", "Aggregation"),
    ("streaming", "Streaming"),
    ("multistream", "Multistream"),
    ("checkpoint", "Checkpoint"),
    ("serve", "Serve"),
    ("parallel", "Parallel"),
]

OUT_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "docs", "api")


def _public_classes(module):
    names = getattr(module, "__all__", None)
    if names is None:
        names = [n for n in dir(module) if n[0].isupper()]
    out = []
    for name in names:
        obj = getattr(module, name, None)
        # only classes defined under this domain package (re-exported core
        # classes like Metric belong to the core docs, not the domain page)
        if inspect.isclass(obj) and obj.__module__.startswith(module.__name__):
            out.append((name, obj))
    return out


def _signature(cls) -> str:
    try:
        return f"{cls.__name__}{inspect.signature(cls)}"  # strips self itself
    except (TypeError, ValueError):
        return f"{cls.__name__}(...)"


def _render_class(name, cls) -> str:
    doc = inspect.getdoc(cls) or "(no docstring)"
    parts = [f"### `{name}`", "", "```python", _signature(cls), "```", "", doc, ""]
    flags = []
    for attr in ("higher_is_better", "is_differentiable", "full_state_update"):
        if hasattr(cls, attr):
            flags.append(f"`{attr}={getattr(cls, attr)}`")
    if flags:
        parts += ["Flags: " + " · ".join(flags), ""]
    return "\n".join(parts)


def main() -> int:
    os.makedirs(OUT_DIR, exist_ok=True)
    index_lines = [
        "# API reference (generated)",
        "",
        "Per-metric pages rendered from live docstrings by",
        "`python -m tools.gen_api_docs` — regenerate after changing metrics.",
        "Narrative per-domain guides live in [`docs/domains/`](../domains/).",
        "",
    ]
    for mod_name, title in DOMAINS:
        module = importlib.import_module(f"metrics_tpu.{mod_name}")
        classes = _public_classes(module)
        lines = [
            f"# {title} API",
            "",
            f"`metrics_tpu.{mod_name}` — {len(classes)} public classes.",
            "Generated from docstrings; see also the narrative guide in",
            f"`docs/domains/`.",
            "",
        ]
        for name, cls in classes:
            lines.append(_render_class(name, cls))
        path = os.path.join(OUT_DIR, f"{mod_name}.md")
        with open(path, "w") as f:
            f.write("\n".join(lines).rstrip() + "\n")
        index_lines.append(f"- [{title}]({mod_name}.md) — {len(classes)} classes")
        print(f"wrote {path} ({len(classes)} classes)")
    with open(os.path.join(OUT_DIR, "README.md"), "w") as f:
        f.write("\n".join(index_lines) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
