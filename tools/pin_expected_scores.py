"""Pin published-score expected values for the weights-gated parity tests.

``tests/test_weights_gated.py`` compares FID/LPIPS/BERTScore computed by
metrics_tpu (with the converted pretrained weights installed by
``tools/fetch_weights.py``) against the REFERENCE stack's outputs on fixed
seeded inputs.  The reference stack (torch + torch-fidelity / lpips /
bert_score, the same dependencies the reference wires in
``/root/reference/src/torchmetrics/image/fid.py:41-58``, ``image/lpip.py:23-43``
and ``text/bert.py:41``) is only available on a machine with network egress,
so the expected values live in a checked-in JSON produced by this script:

    # one-command CI step on a machine with egress:
    python -m tools.fetch_weights --all            # install converted weights
    pip install torch-fidelity lpips bert_score    # reference oracle stack
    python -m tools.pin_expected_scores            # writes the JSON pins
    python -m pytest tests/test_weights_gated.py   # parity, to published stack

The fixed inputs are generated HERE (both the pinning run and the tests
import these generators) so the two stacks always score identical data.
"""

import json
import os

import numpy as np

PINS_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "tests", "expected_weights_scores.json")


def fixed_images(seed: int, n: int = 32, size: int = 299) -> np.ndarray:
    """Deterministic uint8 NCHW image batch (smooth blobs, not white noise —
    feature extractors produce degenerate covariances on pure noise)."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    imgs = np.zeros((n, 3, size, size), np.float32)
    for i in range(n):
        for c in range(3):
            k = rng.integers(1, 5, size=2)
            phase = rng.random(2) * 6.28
            imgs[i, c] = 0.5 + 0.25 * (
                np.sin(6.28 * k[0] * yy + phase[0]) * np.cos(6.28 * k[1] * xx + phase[1])
            )
    imgs += rng.normal(0, 0.05, imgs.shape).astype(np.float32)
    return (np.clip(imgs, 0, 1) * 255).astype(np.uint8)


def fixed_image_pairs(seed: int, n: int = 8, size: int = 64) -> tuple:
    """Deterministic [-1, 1] float NCHW pairs for LPIPS."""
    a = fixed_images(seed, n=n, size=size).astype(np.float32) / 127.5 - 1.0
    b = fixed_images(seed + 1, n=n, size=size).astype(np.float32) / 127.5 - 1.0
    return a, b


def fixed_map_fixture(seed: int = 11, n_img: int = 64) -> tuple:
    """Deterministic 64-image detection fixture hitting the COCO protocol's
    edges the 4-image pinned subset cannot (VERDICT r3 next #9): maxDets
    truncation (130-det images), exact area-range boundary boxes (32² and
    96² areas), det-free and gt-free images, and quantized scores forcing
    ties.  Both the pycocotools pinning run and the gated test consume THIS
    generator, so the two stacks always score identical data.

    Returns (preds, targets) in the metric's dict-per-image xyxy format.
    """
    rng = np.random.default_rng(seed)
    canvas = 640.0
    preds, targets = [], []
    for i in range(n_img):
        n_gt = 0 if i % 13 == 0 else int(rng.integers(1, 10))
        boxes, labels = [], []
        for _ in range(n_gt):
            kind = int(rng.integers(0, 4))
            if kind == 0:
                w = h = 32.0  # exactly the small/medium area boundary
            elif kind == 1:
                w = h = 96.0  # exactly the medium/large area boundary
            else:
                w, h = rng.uniform(4, 200, 2)
            x = rng.uniform(0, canvas - w)
            y = rng.uniform(0, canvas - h)
            boxes.append([x, y, x + w, y + h])
            labels.append(int(rng.integers(0, 7)))
        gt_boxes = np.asarray(boxes, np.float64).reshape(-1, 4)
        gt_labels = np.asarray(labels, np.int64)
        dets, dscores, dlabels = [], [], []
        if n_gt and i % 7 != 0:  # i % 7 == 0 -> image with no detections
            n_det = 130 if i % 17 == 0 else int(rng.integers(1, 14))
            for _ in range(n_det):
                src = int(rng.integers(0, n_gt))
                x1, y1, x2, y2 = gt_boxes[src]
                jit = rng.normal(0, 8, 4)
                dets.append([
                    x1 + jit[0], y1 + jit[1],
                    max(x1 + jit[0] + 4, x2 + jit[2]), max(y1 + jit[1] + 4, y2 + jit[3]),
                ])
                dscores.append(round(float(rng.random()), 2))  # 2-decimal ties
                dlabels.append(int(gt_labels[src]) if rng.random() < 0.8 else int(rng.integers(0, 7)))
        preds.append(dict(
            boxes=np.asarray(dets, np.float64).reshape(-1, 4),
            scores=np.asarray(dscores, np.float64),
            labels=np.asarray(dlabels, np.int64),
        ))
        targets.append(dict(boxes=gt_boxes, labels=gt_labels))
    return preds, targets


def fixed_sentence_pairs() -> tuple:
    preds = [
        "the quick brown fox jumps over the lazy dog",
        "a stitch in time saves nine",
        "machine translation quality estimation remains difficult",
        "the committee approved the annual budget on tuesday",
    ]
    target = [
        "a quick brown fox leapt over the lazy dog",
        "a stitch in time saves nine lives",
        "estimating the quality of machine translation is hard",
        "on tuesday the committee passed the yearly budget",
    ]
    return preds, target


def main() -> int:
    pins = {}
    import torch  # the oracle stack is torch-based

    # ---- FID-2048 via torch-fidelity's InceptionV3 (the reference extractor)
    try:
        from torch_fidelity.feature_extractor_inceptionv3 import FeatureExtractorInceptionV3

        net = FeatureExtractorInceptionV3("inception", ["2048"])
        net.eval()

        def feats(imgs):
            with torch.no_grad():
                return net(torch.from_numpy(imgs))[0].numpy().astype(np.float64)

        real = feats(fixed_images(0))
        fake = feats(fixed_images(100))
        mu1, mu2 = real.mean(0), fake.mean(0)
        s1 = np.cov(real, rowvar=False)
        s2 = np.cov(fake, rowvar=False)
        import scipy.linalg

        covmean = scipy.linalg.sqrtm(s1 @ s2).real
        pins["fid_2048"] = float(((mu1 - mu2) ** 2).sum() + np.trace(s1 + s2 - 2 * covmean))
    except Exception as err:  # noqa: BLE001
        print(f"fid pin skipped: {err}")

    # ---- COCO-protocol mAP on the 64-image mixed fixture via pycocotools
    # (the official oracle; needs `pip install pycocotools` on the pin box)
    try:
        from pycocotools.coco import COCO
        from pycocotools.cocoeval import COCOeval

        preds, targets = fixed_map_fixture()
        gt_anns, ann_id = [], 1
        for i, t in enumerate(targets):
            for box, label in zip(t["boxes"], t["labels"]):
                x1, y1, x2, y2 = (float(v) for v in box)
                gt_anns.append({
                    "id": ann_id, "image_id": i, "category_id": int(label),
                    "bbox": [x1, y1, x2 - x1, y2 - y1],
                    "area": (x2 - x1) * (y2 - y1), "iscrowd": 0,
                })
                ann_id += 1
        coco_gt = COCO()
        coco_gt.dataset = {
            "images": [{"id": i} for i in range(len(targets))],
            "annotations": gt_anns,
            "categories": [{"id": c} for c in range(7)],
        }
        coco_gt.createIndex()
        dt = []
        for i, p in enumerate(preds):
            for box, score, label in zip(p["boxes"], p["scores"], p["labels"]):
                x1, y1, x2, y2 = (float(v) for v in box)
                dt.append({
                    "image_id": i, "category_id": int(label),
                    "bbox": [x1, y1, x2 - x1, y2 - y1], "score": float(score),
                })
        coco_dt = coco_gt.loadRes(dt)
        ev = COCOeval(coco_gt, coco_dt, "bbox")
        ev.evaluate()
        ev.accumulate()
        ev.summarize()
        keys = ["map", "map_50", "map_75", "map_small", "map_medium", "map_large",
                "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large"]
        pins["map_coco_64"] = {k: float(v) for k, v in zip(keys, ev.stats)}
    except Exception as err:  # noqa: BLE001
        print(f"map fixture pin skipped: {err}")

    # ---- LPIPS backbones via the lpips package (the reference extractor)
    for net_type in ("vgg", "alex", "squeeze"):
        try:
            import lpips

            model = lpips.LPIPS(net=net_type)
            a, b = fixed_image_pairs(7)
            with torch.no_grad():
                d = model(torch.from_numpy(a), torch.from_numpy(b)).reshape(-1).numpy()
            pins[f"lpips_{net_type}"] = float(d.mean())
        except Exception as err:  # noqa: BLE001
            print(f"lpips {net_type} pin skipped: {err}")

    # ---- BERTScore roberta-large F1 via bert_score (the reference oracle)
    try:
        from bert_score import score as bert_score_fn

        preds, target = fixed_sentence_pairs()
        _, _, f1 = bert_score_fn(preds, target, lang="en", model_type="roberta-large",
                                 num_layers=17, idf=False, rescale_with_baseline=False)
        pins["bertscore_roberta_large_f1"] = [float(x) for x in f1]
    except Exception as err:  # noqa: BLE001
        print(f"bertscore pin skipped: {err}")

    existing = {}
    if os.path.exists(PINS_PATH):
        with open(PINS_PATH) as f:
            existing = json.load(f)
    existing.update(pins)
    with open(PINS_PATH, "w") as f:
        json.dump(existing, f, indent=2, sort_keys=True)
    print(f"wrote {sorted(pins)} -> {PINS_PATH}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
