"""Incremental (``--changed``) mode for the analysis CLI.

The pre-commit path: re-analyze only the modules that changed since the
last run, plus everything that can reach them through the call graph, and
splice the fresh findings into the cached ones for the rest of the
package.

Change detection is two-layered:

* ``git diff --name-only HEAD`` (plus untracked files) names what differs
  from the committed tree — this is what a pre-commit hook cares about.
* A content-hash cache (``tools/analyze/.cache.json``, not checked in)
  remembers the exact source each module had when it was last analyzed.
  A file git reports as changed but whose hash matches the cache was
  already analyzed in this exact state and is skipped, so the warm
  no-change invocation does no AST work at all and finishes well under
  two seconds.

A dirty module's *dependents* — the transitive reverse module-dependency
closure from the call graph — are re-analyzed with it, because the
cross-module passes (lock-order chains, trace-safety regions,
serve-blocking reachability) can change their findings when a callee
changes.  The ``finish`` halves still see the whole package (see
``run_passes(only=...)``), so provenance chains stay complete.

Dynamic passes (lock-witness, state-race) are skipped here on purpose:
they import jax and drive the serve burst, which blows the fast-path
budget.  The full ``python -m tools.analyze`` run remains the authority;
``--changed`` is the quick gate in front of it.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
from typing import Any, Dict, List, Optional, Set, Tuple

from tools.analyze import engine
from tools.analyze.callgraph import build_call_graph

CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".cache.json")
_CACHE_VERSION = 1


def _hash(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8")).hexdigest()[:16]


def git_changed_rels(root: str) -> Optional[Set[str]]:
    """Package ``.py`` rels that differ from HEAD (tracked diff + untracked).

    ``None`` when git is unavailable (not a repo, no binary) — callers fall
    back to pure hash-based detection, which is a superset anyway.
    """
    rels: Set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            out = subprocess.run(
                cmd, cwd=root, capture_output=True, text=True, timeout=30.0
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0:
            return None
        rels.update(
            line.strip()
            for line in out.stdout.splitlines()
            if line.strip().startswith(engine.PACKAGE + "/")
            and line.strip().endswith(".py")
        )
    return rels


def _load_cache(path: str, static_passes: List[str]) -> Optional[Dict[str, Any]]:
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    if data.get("version") != _CACHE_VERSION or data.get("passes") != static_passes:
        return None  # pass set changed: cached findings are not comparable
    return data


def _save_cache(
    path: str,
    static_passes: List[str],
    hashes: Dict[str, str],
    raw: List[engine.Finding],
) -> None:
    payload = {
        "version": _CACHE_VERSION,
        "passes": static_passes,
        "hashes": hashes,
        "findings": [f.to_json() for f in raw],
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def run_changed(
    root: Optional[str] = None,
    cache_path: str = CACHE_PATH,
    baseline_path: Optional[str] = engine.BASELINE_PATH,
) -> Tuple[engine.Report, Dict[str, Any]]:
    """Incremental static run; returns ``(report, info)``.

    ``info`` carries the incremental telemetry the CLI prints: which rels
    were dirty, how many dependents rode along, and whether the cache was
    warm.  The report is shaped exactly like a full run's so callers (and
    exit-code logic) need not care which mode produced it.
    """
    from tools.analyze import passes as _passes  # noqa: F401  (register)

    root = os.path.abspath(root or engine.REPO_ROOT)
    static_passes = sorted(
        n for n, p in engine.PASSES.items() if p.kind == "ast"
    )
    units = engine.discover_units(root)
    hashes = {u.rel: _hash(u.source) for u in units}
    cache = _load_cache(cache_path, static_passes)
    git_rels = git_changed_rels(root)

    if cache is None:
        # cold start: full static run seeds the cache
        report = engine.run_passes(static_passes, root=root, units=units,
                                   baseline_path=None, collect_all=True)
        raw = report.findings
        dirty: Set[str] = set(hashes)
        affected: Set[str] = set(hashes)
        warm = False
    else:
        cached_hashes: Dict[str, str] = cache.get("hashes", {})
        dirty = {rel for rel, h in hashes.items() if cached_hashes.get(rel) != h}
        cached_raw = [
            engine.Finding(**f)
            for f in cache.get("findings", [])
            if f.get("module") in hashes  # drop findings of deleted modules
        ]
        if not dirty:
            raw = cached_raw
            affected = set()
            warm = True
        else:
            graph = build_call_graph(units)
            affected = dirty | graph.dependents(dirty)
            report = engine.run_passes(static_passes, root=root, units=units,
                                       baseline_path=None, collect_all=True,
                                       only=affected)
            # fresh findings own every affected module; keep the cache's
            # findings for the rest, deduping cross-module emissions that
            # exist on both sides (frozen dataclass == full-tuple equality)
            kept = [f for f in cached_raw if f.module not in affected]
            raw = list(dict.fromkeys(kept + report.findings))
            warm = False

    _save_cache(cache_path, static_passes, hashes, raw)

    baseline = engine.load_baseline(baseline_path) if baseline_path else {}
    fresh, suppressed = engine.split_baselined(raw, baseline)
    per_pass = {name: {"findings": 0, "baselined": 0} for name in static_passes}
    for f in fresh:
        per_pass.setdefault(f.pass_name, {"findings": 0, "baselined": 0})
        per_pass[f.pass_name]["findings"] += 1
    for f in suppressed:
        per_pass.setdefault(f.pass_name, {"findings": 0, "baselined": 0})
        per_pass[f.pass_name]["baselined"] += 1
    report = engine.Report(
        findings=fresh,
        baselined=suppressed,
        per_pass=per_pass,
        modules_analyzed=len(affected),
    )
    info = {
        "warm": warm,
        "dirty": sorted(dirty),
        "analyzed": len(affected),
        "dependents": len(affected - dirty),
        "git_changed": sorted(git_rels) if git_rels is not None else None,
        "static_passes": static_passes,
    }
    return report, info
