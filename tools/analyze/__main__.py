"""CLI for the unified static-analysis engine.

    python -m tools.analyze                 # run every pass, human output
    python -m tools.analyze --json          # machine-readable report
    python -m tools.analyze --pass lock-order --pass trace-safety
    python -m tools.analyze --changed       # incremental pre-commit gate
    python -m tools.analyze --list-passes
    python -m tools.analyze --update-baseline

Exit status: 0 when every finding is baselined (or none), 1 when any fresh
finding exists, 2 on usage errors.  ``--update-baseline`` rewrites
``tools/analyze/baseline.json`` from the current findings (preserving
existing justifications, reporting fingerprints that disappeared on
stderr) and exits 0.  ``--changed`` re-analyzes only modules that changed
plus their call-graph dependents (static passes only — see
``tools/analyze/incremental.py``); a warm no-change run finishes in well
under two seconds, which is what makes it usable as a pre-commit hook.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.analyze import engine


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="Run the metrics_tpu static-analysis passes.",
    )
    parser.add_argument(
        "--pass",
        dest="passes",
        action="append",
        metavar="NAME",
        help="run only this pass (repeatable; default: all)",
    )
    parser.add_argument("--json", action="store_true", help="emit a JSON report")
    parser.add_argument(
        "--list-passes", action="store_true", help="list registered passes and exit"
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite baseline.json from the current findings and exit 0",
    )
    parser.add_argument(
        "--changed",
        action="store_true",
        help=(
            "incremental mode: analyze only changed modules plus their "
            "call-graph dependents (static passes only)"
        ),
    )
    parser.add_argument(
        "--show-baselined",
        action="store_true",
        help="also print findings absorbed by the baseline",
    )
    parser.add_argument("--root", default=None, help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    from tools.analyze import passes as _passes  # noqa: F401  (register)

    if args.list_passes:
        for name in sorted(engine.PASSES):
            p = engine.PASSES[name]
            print(f"{name:22s} [{p.kind}] {p.description}")
        return 0

    if args.changed:
        if args.update_baseline or args.passes:
            print(
                "--changed runs the full static pass set and never rewrites "
                "the baseline; drop --pass/--update-baseline",
                file=sys.stderr,
            )
            return 2
        from tools.analyze import incremental

        report, info = incremental.run_changed(root=args.root)
        if args.json:
            payload = report.to_json()
            payload["changed"] = info
            print(json.dumps(payload, indent=2, sort_keys=True))
            return 0 if report.ok else 1
        for f in report.findings:
            print(f.render())
        if args.show_baselined:
            for f in report.baselined:
                print(f"(baselined) {f.render()}")
        mode = (
            "cache warm, nothing changed"
            if info["warm"]
            else f"{len(info['dirty'])} dirty + {info['dependents']} dependent(s)"
        )
        print(
            f"{len(report.findings)} finding(s), {len(report.baselined)} "
            f"baselined, {report.modules_analyzed} module(s) re-analyzed "
            f"({mode}; static passes only)"
        )
        return 0 if report.ok else 1

    try:
        report = engine.run_passes(
            pass_names=args.passes,
            root=args.root,
            collect_all=args.update_baseline,
        )
    except KeyError as err:
        print(err.args[0], file=sys.stderr)
        return 2

    if args.update_baseline:
        previous = set(engine.load_baseline())
        entries = engine.update_baseline(report.findings)
        # fingerprints that disappeared are *fixed* findings — surface them
        # so the fix gets celebrated (and the justification text retired)
        for key in sorted(previous - set(entries)):
            print(f"fixed (removed from baseline): {key}", file=sys.stderr)
        print(
            f"baseline rewritten: {len(entries)} fingerprint(s) covering "
            f"{len(report.findings)} finding(s)"
        )
        todo = [k for k, e in entries.items() if e["justification"].startswith("TODO")]
        for key in todo:
            print(f"  needs justification: {key}")
        return 0

    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
        return 0 if report.ok else 1

    for f in report.findings:
        print(f.render())
    if args.show_baselined:
        for f in report.baselined:
            print(f"(baselined) {f.render()}")
    print(
        f"{len(report.findings)} finding(s), {len(report.baselined)} baselined, "
        f"{report.modules_analyzed} modules, "
        f"{len(report.per_pass)} pass(es): "
        + ", ".join(
            f"{name}={stats['findings']}+{stats['baselined']}b"
            for name, stats in sorted(report.per_pass.items())
        )
    )
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
