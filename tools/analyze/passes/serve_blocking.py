"""serve-blocking: serve request paths never block on a collective or KV wait.

The serve layer's responsiveness claim is structural: an HTTP handler or
the queue-consumer loop must never sit in a mesh collective, a distributed
barrier, or a parked key-value wait, because a peer that died (or a
scheduler that paused it) would turn one slow tenant read into a hung
service.  ``MetricRegistry.register`` enforces the dynamic half (it forces
``sync_on_compute`` / ``dist_sync_on_step`` off); this pass enforces the
static half: the request-path modules simply do not *spell* any blocking
primitive.

Scope is every module under ``metrics_tpu/serve/`` found by the package
walk — a NEW serve module is a request-path module until it explicitly
opts out.  ``server.py`` (the durability loop checkpoints, which barriers
across ranks by design) and ``soak.py`` (the harness fires explicit
operator syncs) carry ``# analyze: skip-file[serve-blocking]`` markers.

This pass is the ported ``tools/serve_lint.py`` (its module entry point
remains as a shim).
"""

from __future__ import annotations

import ast
from typing import List

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    ModuleUnit,
    register_pass,
    walk_with_scope,
)

SCOPE_PREFIX = "metrics_tpu/serve/"

# call names that block on peers: collectives, barriers, KV-store waits,
# checkpoint commits (which barrier internally), and explicit metric syncs
BLOCKING_CALLS = {
    "sync",
    "unsync",
    "sync_context",
    "wait_at_barrier",
    "blocking_key_value_get",
    "blocking_key_value_get_bytes",
    "all_gather",
    "all_gather_bytes",
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "preflight_check",
    "save",
    "save_now",
    "maybe_save",
    "restore",
    "barrier",
}

# importing the distributed/checkpoint machinery into a request-path module
# is the gateway violation — flag it at the import, where intent is clearest
BANNED_IMPORT_PREFIXES = (
    "metrics_tpu.parallel",
    "metrics_tpu.checkpoint",
    "jax.experimental.multihost_utils",
)


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


@register_pass
class ServeBlockingPass(AnalysisPass):
    name = "serve-blocking"
    description = (
        "serve request-path modules spell no blocking collective, barrier, "
        "KV wait, or checkpoint commit, and never import the distributed "
        "machinery"
    )

    def applies(self, unit: ModuleUnit) -> bool:
        return unit.rel.startswith(SCOPE_PREFIX)

    def check_module(self, unit: ModuleUnit, ctx: AnalysisContext) -> List[Finding]:
        problems: List[Finding] = []
        for node, scope in walk_with_scope(unit.tree):
            where = scope or "<module>"
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name in BLOCKING_CALLS:
                    problems.append(
                        self.finding(
                            unit.rel,
                            node.lineno,
                            "blocking-call",
                            f"{where}:{name}",
                            f"`{name}(...)` can block on a peer; request paths "
                            "must read local state only (move it to server.py's "
                            "durability loop or an operator action)",
                        )
                    )
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                if isinstance(node, ast.Import):
                    names = [a.name for a in node.names]
                else:
                    mod = node.module or ""
                    names = [mod] + [f"{mod}.{a.name}" for a in node.names]
                for name in names:
                    if any(
                        name == p or name.startswith(p + ".")
                        for p in BANNED_IMPORT_PREFIXES
                    ):
                        problems.append(
                            self.finding(
                                unit.rel,
                                node.lineno,
                                "banned-import",
                                f"{where}:{name}",
                                f"imports `{name}`; the distributed/checkpoint "
                                "machinery must stay out of request-path "
                                "modules",
                            )
                        )
        return problems
