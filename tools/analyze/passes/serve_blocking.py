"""serve-blocking: serve request paths never block on a collective or KV wait.

The serve layer's responsiveness claim is structural: an HTTP handler or
the queue-consumer loop must never sit in a mesh collective, a distributed
barrier, or a parked key-value wait, because a peer that died (or a
scheduler that paused it) would turn one slow tenant read into a hung
service.  ``MetricRegistry.register`` enforces the dynamic half (it forces
``sync_on_compute`` / ``dist_sync_on_step`` off); this pass enforces the
static half: the request-path modules simply do not *spell* any blocking
primitive.

Scope is every module under ``metrics_tpu/serve/`` found by the package
walk — a NEW serve module is a request-path module until it explicitly
opts out.  ``server.py`` (the durability loop checkpoints, which barriers
across ranks by design) and ``soak.py`` (the harness fires explicit
operator syncs) carry ``# analyze: skip-file[serve-blocking]`` markers.

Since the call-graph migration the lexical rule has a transitive sibling,
``blocking-reachable``: a request-path function whose resolvable call
chain (``tools/analyze/callgraph.py``, bounded by
:attr:`ServeBlockingPass.depth`) lands in a function that *spells* a
blocking primitive — wherever that function lives — is reported with the
full chain, closing the "hide the collective behind one hop" loophole the
old one-module lint had.  The finding lands on the request-path module
(the root owns its transitive behavior), so ``skip-file`` opt-outs keep
their meaning.

This pass is the ported ``tools/serve_lint.py`` (its module entry point
remains as a shim).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    ModuleUnit,
    register_pass,
    walk_with_scope,
)

SCOPE_PREFIX = "metrics_tpu/serve/"

_SCRATCH = "serve-blocking"

# call edges followed below a request-path function before the search stops
DEFAULT_DEPTH = 4

# call names that block on peers: collectives, barriers, KV-store waits,
# checkpoint commits (which barrier internally), explicit metric syncs, and
# disk barriers (fsync parks the caller until the device flushes — only the
# WAL's dedicated writer thread may pay that, and wal.py opts its four call
# sites out line-by-line with reasons rather than skipping the whole file)
BLOCKING_CALLS = {
    "sync",
    "unsync",
    "sync_context",
    "wait_at_barrier",
    "blocking_key_value_get",
    "blocking_key_value_get_bytes",
    "all_gather",
    "all_gather_bytes",
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "preflight_check",
    "save",
    "save_now",
    "maybe_save",
    "restore",
    "barrier",
    "fsync",
}

# importing the distributed/checkpoint machinery into a request-path module
# is the gateway violation — flag it at the import, where intent is clearest
BANNED_IMPORT_PREFIXES = (
    "metrics_tpu.parallel",
    "metrics_tpu.checkpoint",
    "jax.experimental.multihost_utils",
)


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


@register_pass
class ServeBlockingPass(AnalysisPass):
    name = "serve-blocking"
    description = (
        "serve request-path modules spell no blocking collective, barrier, "
        "KV wait, or checkpoint commit (directly or through any resolvable "
        "call chain), and never import the distributed machinery"
    )

    def __init__(self) -> None:
        self.depth = DEFAULT_DEPTH

    def applies(self, unit: ModuleUnit) -> bool:
        return unit.rel.startswith(SCOPE_PREFIX)

    def check_module(self, unit: ModuleUnit, ctx: AnalysisContext) -> List[Finding]:
        from tools.analyze.callgraph import collect_functions

        scratch = ctx.scratch.setdefault(_SCRATCH, {"roots": []})
        funcs, _classes = collect_functions(unit.tree, unit.rel)
        scratch["roots"].extend(f.fid for f in funcs)
        problems: List[Finding] = []
        for node, scope in walk_with_scope(unit.tree):
            where = scope or "<module>"
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name in BLOCKING_CALLS:
                    problems.append(
                        self.finding(
                            unit.rel,
                            node.lineno,
                            "blocking-call",
                            f"{where}:{name}",
                            f"`{name}(...)` can block on a peer; request paths "
                            "must read local state only (move it to server.py's "
                            "durability loop or an operator action)",
                        )
                    )
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                if isinstance(node, ast.Import):
                    names = [a.name for a in node.names]
                else:
                    mod = node.module or ""
                    names = [mod] + [f"{mod}.{a.name}" for a in node.names]
                for name in names:
                    if any(
                        name == p or name.startswith(p + ".")
                        for p in BANNED_IMPORT_PREFIXES
                    ):
                        problems.append(
                            self.finding(
                                unit.rel,
                                node.lineno,
                                "banned-import",
                                f"{where}:{name}",
                                f"imports `{name}`; the distributed/checkpoint "
                                "machinery must stay out of request-path "
                                "modules",
                            )
                        )
        return problems

    # ------------------------------------------------------------- closure
    def _spelled_blocking(self, fid: str, ctx: AnalysisContext) -> Optional[str]:
        """The first blocking-call name a function's own body spells, cached."""
        from tools.analyze.callgraph import body_nodes, get_call_graph

        scratch = ctx.scratch.setdefault(_SCRATCH, {"roots": []})
        cache: Dict[str, Optional[str]] = scratch.setdefault("spells", {})
        if fid in cache:
            return cache[fid]
        node = get_call_graph(ctx).node(fid)
        spelled: Optional[str] = None
        if node is not None:
            for n in body_nodes(node.node):
                if isinstance(n, ast.Call) and _call_name(n) in BLOCKING_CALLS:
                    spelled = _call_name(n)
                    break
        cache[fid] = spelled
        return spelled

    def finish(self, ctx: AnalysisContext) -> List[Finding]:
        from tools.analyze.callgraph import get_call_graph

        scratch = ctx.scratch.get(_SCRATCH)
        if not scratch or not scratch["roots"]:
            return []
        graph = get_call_graph(ctx)
        problems: List[Finding] = []
        for root_fid in sorted(scratch["roots"]):
            root = graph.node(root_fid)
            if root is None:
                continue
            reached = graph.chains([(root_fid, 0)], depth=self.depth)
            for callee_fid in sorted(reached):
                if callee_fid == root_fid:
                    continue  # the lexical rule already owns direct spellings
                callee = graph.node(callee_fid)
                if callee is None:
                    continue
                callee_unit = ctx.unit(callee.rel)
                if (
                    callee.rel.startswith(SCOPE_PREFIX)
                    and callee_unit is not None
                    and not callee_unit.skips(self.name)
                ):
                    continue  # in-scope callee: its own lexical findings cover it
                name = self._spelled_blocking(callee_fid, ctx)
                if name is None:
                    continue
                chain = reached[callee_fid]
                chain_quals = [graph.display(c) for c, _ in chain[1:]]
                problems.append(
                    self.finding(
                        root.rel,
                        chain[1][1] if len(chain) > 1 else root.lineno,
                        "blocking-reachable",
                        f"{root.qualname}->{callee.qualname}:{name}",
                        f"request path `{root.qualname}` reaches `{name}(...)` "
                        f"via {root.qualname} -> {' -> '.join(chain_quals)} — "
                        "blocking on a peer one hop away is still blocking; "
                        "move the call behind the durability loop or an "
                        "operator action",
                    )
                )
        return problems
