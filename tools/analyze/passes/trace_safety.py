"""trace-safety: no host round-trips or Python control flow on traced values.

The library's perf story rests on jitted state math: ``update`` / ``compute``
/ the sync transport all trace once and then dispatch.  A ``.item()``, a
``float(...)`` cast, an ``np.asarray`` of a device array, or a Python
``if``/``while`` on a traced value inside that region either crashes under
jit (``TracerBoolConversionError``) or — worse — silently forces a host
sync or a retrace per batch.  This pass generalizes the old streaming-only
shape lint to the whole package:

1. **traced-region discovery** — a function is traced when it is decorated
   with ``jit``/``pjit`` (directly or through ``functools.partial``), passed
   to a ``jax.jit`` / ``pjit`` / ``vmap`` / ``pmap`` / ``lax.cond`` /
   ``lax.scan`` / ``lax.while_loop`` / ... call site (by name, ``self.``
   attribute, or inline lambda), or statically reachable from such a root
   through the whole-package call graph (``tools/analyze/callgraph.py``) —
   cross-module, depth-bounded by :attr:`TraceSafetyPass.depth`, with the
   provenance chain printed in the finding (``traced via update ->
   _merge_bins``);
2. **host round-trips** inside traced regions: ``.item()`` / ``.tolist()``
   (rule ``host-pull``), ``float()``/``int()``/``bool()`` casts of
   non-constant values (rule ``host-cast`` — shape/ndim/size/len reads are
   exempt, those are static under trace), and host-numpy ``asarray`` /
   ``array`` calls where the **import graph** says the alias is real
   ``numpy``, not ``jax.numpy`` (rule ``numpy-in-trace``);
3. **Python control flow on traced values** (rule ``traced-branch``,
   severity ``warning``): an ``if``/``while`` whose test reads a function
   parameter or a value produced by a ``jax.numpy``/``jax.lax`` call —
   parameters with literal defaults or scalar annotations are treated as
   static configuration, and ``is None`` / ``isinstance`` / shape reads are
   exempt.

Deliberately-eager paths (the detection host kernels, the native ctypes
shims, serve I/O) are allowlisted below; one-off eager lines inside traced
modules use ``# analyze: ignore[trace-safety]`` with a reason.  Functions
the closure reaches inside an allowlisted module are not reported either —
crossing into a host kernel is the *call site's* problem, and the
serve-blocking pass owns that boundary.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    ModuleUnit,
    register_pass,
)

# resolved dotted name -> positional indices that are functions-to-trace.
# Positions matter: ``lax.scan(body, state, xs)`` traces only ``body`` —
# marking operand names too would alias unrelated same-named defs (e.g. a
# ``state`` carry colliding with a ``state`` property).
FUNC_ARG_POSITIONS = {
    "jax.jit": (0,),
    "jax.pjit": (0,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.experimental.pjit.pjit": (0,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.map": (0,),
    "jax.lax.associative_scan": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.jvp": (0,),
    "jax.vjp": (0,),
    "jax.linearize": (0,),
}

# keyword spellings of those function arguments
FUNC_KWARG_NAMES = {"fun", "f", "body_fun", "cond_fun", "true_fun", "false_fun", "branches"}

TRACE_WRAPPERS = frozenset(FUNC_ARG_POSITIONS)

# resolved call prefixes that produce traced/device values
DEVICE_NAMESPACES = (
    "jax.numpy.",
    "jax.lax.",
    "jax.nn.",
    "jax.random.",
    "jax.scipy.",
)

# host-numpy functions that force a device->host transfer of a traced value
NUMPY_HOST_CALLS = {"asarray", "array", "ascontiguousarray", "copy", "frombuffer"}

HOST_PULL_CALLS = {"item", "tolist"}
HOST_CASTS = {"float", "int", "bool", "complex"}

# static-under-trace attribute reads: casting these is fine
STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "weak_type", "itemsize"}

SCALAR_ANNOTATIONS = {"int", "bool", "str", "bytes"}

# deliberately-eager module prefixes: host kernels and serve/O — the paths
# that are eager BY DESIGN (finer-grained opt-outs use skip-file markers)
EAGER_ALLOWLIST = (
    # mean_ap.py is the host orchestration/IO layer ONLY — the jitted mAP
    # kernels live in detection/device.py, which must stay off this list
    "metrics_tpu/detection/mean_ap.py",
    "metrics_tpu/_native/",  # ctypes build + host shims
    "metrics_tpu/serve/httpd.py",  # HTTP I/O is host-side by definition
    "metrics_tpu/serve/soak.py",  # soak harness drives the server eagerly
    "metrics_tpu/serve/traffic.py",  # traffic generator is host-side
)

_SCRATCH = "trace-safety"

# call edges followed below a traced root before the closure gives up
DEFAULT_DEPTH = 6


def _body_nodes(fn: ast.AST):
    """Walk a function's own body, not descending into nested defs/lambdas."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
            ):
                continue
            stack.append(child)


def _contains(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


def _is_static_read(expr: ast.AST) -> bool:
    """shape/len/dtype reads are static under trace — casting them is fine."""
    return _contains(
        expr,
        lambda n: (isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS)
        or (isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and n.func.id == "len"),
    )


def _param_names(fn: ast.AST) -> List[Tuple[str, Optional[ast.AST], Optional[ast.AST]]]:
    """``(name, default, annotation)`` for every positional/kw-only param."""
    a = fn.args
    out: List[Tuple[str, Optional[ast.AST], Optional[ast.AST]]] = []
    pos = list(a.posonlyargs) + list(a.args)
    defaults: List[Optional[ast.AST]] = [None] * (len(pos) - len(a.defaults)) + list(a.defaults)
    for arg, default in zip(pos, defaults):
        out.append((arg.arg, default, arg.annotation))
    for arg, default in zip(a.kwonlyargs, a.kw_defaults):
        out.append((arg.arg, default, arg.annotation))
    return out


def _is_literal_default(default: Optional[ast.AST]) -> bool:
    if default is None:
        return False
    if isinstance(default, ast.Constant):
        return True
    if isinstance(default, ast.UnaryOp) and isinstance(default.operand, ast.Constant):
        return True
    return False


def _scalar_annotation(annotation: Optional[ast.AST]) -> bool:
    if annotation is None:
        return False
    names = {n.id for n in ast.walk(annotation) if isinstance(n, ast.Name)}
    return bool(names & SCALAR_ANNOTATIONS) and not (names - SCALAR_ANNOTATIONS - {"Optional"})


def _static_argnames(fn: ast.AST, unit: ModuleUnit) -> Set[str]:
    """Params pinned static by a jit decorator's static_argnames/argnums."""
    out: Set[str] = set()
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return out
    params = [name for name, _d, _a in _param_names(fn)]
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        resolved = unit.resolve(dec.func)
        is_jit = resolved in TRACE_WRAPPERS or (
            resolved == "functools.partial"
            and dec.args
            and unit.resolve(dec.args[0]) in TRACE_WRAPPERS
        )
        if not is_jit:
            continue
        for kw in dec.keywords:
            if kw.arg not in ("static_argnames", "static_argnums"):
                continue
            values = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            for v in values:
                if not isinstance(v, ast.Constant):
                    continue
                if isinstance(v.value, str):
                    out.add(v.value)
                elif isinstance(v.value, int) and 0 <= v.value < len(params):
                    out.add(params[v.value])
    return out


def _arrayish_names(fn: ast.AST, unit: ModuleUnit) -> Set[str]:
    """Names in ``fn`` that plausibly hold traced values: non-config
    parameters plus names assigned from jax namespace calls."""
    names: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        static = _static_argnames(fn, unit)
        for name, default, annotation in _param_names(fn):
            if name in ("self", "cls") or name in static:
                continue
            if _is_literal_default(default) or _scalar_annotation(annotation):
                continue  # static configuration, not data
            names.add(name)
    for node in _body_nodes(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            resolved = unit.resolve(node.value.func)
            if resolved and resolved.startswith(DEVICE_NAMESPACES):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        names.add(target.id)
    return names


def _test_is_exempt(test: ast.AST) -> bool:
    """``is None`` / isinstance / shape reads etc. are static predicates."""
    for n in ast.walk(test):
        if isinstance(n, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for op in n.ops
        ):
            return True
        if isinstance(n, ast.Compare) and any(
            isinstance(c, ast.Constant) and isinstance(c.value, str)
            for c in [n.left] + list(n.comparators)
        ):
            return True  # comparing against a string: mode/kind dispatch
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and n.func.id in (
            "isinstance",
            "len",
            "hasattr",
            "getattr",
            "callable",
        ):
            return True
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            return True
    return False


@register_pass
class TraceSafetyPass(AnalysisPass):
    name = "trace-safety"
    description = (
        "functions reachable from jit/pjit/vmap call sites (closed over the "
        "whole-package call graph) contain no host round-trips "
        "(.item()/float()/np.asarray) or Python branches on traced values"
    )

    def __init__(self) -> None:
        self.depth = DEFAULT_DEPTH

    def applies(self, unit: ModuleUnit) -> bool:
        return not unit.rel.startswith(EAGER_ALLOWLIST)

    # ------------------------------------------------------------ discovery
    def _module_roots(self, unit: ModuleUnit) -> Set[str]:
        """Traced-region ROOTS of one module: decorated functions plus
        function-position arguments of trace-wrapper call sites.  The
        closure below them happens in :meth:`finish`, over the package
        call graph rather than this module alone."""
        from tools.analyze.callgraph import collect_functions

        tree = unit.tree
        funcs, _classes = collect_functions(tree, unit.rel)
        by_node = {id(f.node): f for f in funcs}
        by_simple: Dict[str, List[str]] = {}
        for f in funcs:
            by_simple.setdefault(f.simple, []).append(f.fid)

        roots: Set[str] = set()

        def mark_name(name: str) -> None:
            roots.update(by_simple.get(name, ()))

        def mark_arg(arg: ast.AST) -> None:
            if isinstance(arg, ast.Name):
                mark_name(arg.id)
            elif isinstance(arg, ast.Attribute):
                mark_name(arg.attr)  # self.fn / obj.fn — match by method name
            elif isinstance(arg, ast.Lambda):
                info = by_node.get(id(arg))
                if info is not None:
                    roots.add(info.fid)
            elif isinstance(arg, (ast.List, ast.Tuple)):  # lax.switch branches
                for elt in arg.elts:
                    mark_arg(elt)

        # decorators
        for f in funcs:
            if not isinstance(f.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in f.node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                resolved = unit.resolve(target)
                if resolved in TRACE_WRAPPERS:
                    roots.add(f.fid)
                elif (
                    isinstance(dec, ast.Call)
                    and resolved == "functools.partial"
                    and dec.args
                    and unit.resolve(dec.args[0]) in TRACE_WRAPPERS
                ):
                    roots.add(f.fid)

        # call sites: jax.jit(fn), lax.cond(p, true_fn, false_fn, ...) etc. —
        # only the function-position arguments, never the operands
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            positions = FUNC_ARG_POSITIONS.get(unit.resolve(node.func) or "")
            if positions is None:
                continue
            for i in positions:
                if i < len(node.args):
                    mark_arg(node.args[i])
            for kw in node.keywords:
                if kw.arg in FUNC_KWARG_NAMES and kw.value is not None:
                    mark_arg(kw.value)

        return roots

    def check_module(self, unit: ModuleUnit, ctx: AnalysisContext) -> List[Finding]:
        scratch = ctx.scratch.setdefault(_SCRATCH, {"roots": []})
        scratch["roots"].extend((fid, 0) for fid in sorted(self._module_roots(unit)))
        return []

    # -------------------------------------------------------------- closure
    def finish(self, ctx: AnalysisContext) -> List[Finding]:
        from tools.analyze.callgraph import get_call_graph

        scratch = ctx.scratch.get(_SCRATCH)
        if not scratch or not scratch["roots"]:
            return []
        graph = get_call_graph(ctx)
        reached = graph.chains(scratch["roots"], depth=self.depth)
        problems: List[Finding] = []
        for fid in sorted(reached):
            node = graph.node(fid)
            if node is None or node.rel.startswith(EAGER_ALLOWLIST):
                continue  # crossing into a host kernel is the call site's call
            unit = ctx.unit(node.rel)
            if unit is None:
                continue
            chain = reached[fid]
            via = (
                f" (traced via {graph.render_chain(chain)})" if len(chain) > 1 else ""
            )
            problems.extend(self._check_function(unit, node.qualname, node.node, via))
        return problems

    # -------------------------------------------------------------- checks
    def _check_function(
        self, unit: ModuleUnit, qual: str, fn: ast.AST, via: str
    ) -> List[Finding]:
        problems: List[Finding] = []
        arrayish = _arrayish_names(fn, unit)
        for node in _body_nodes(fn):
            if isinstance(node, ast.Call):
                problems.extend(self._check_call(unit, qual, node, arrayish, via))
            elif isinstance(node, (ast.If, ast.While)):
                kind = "if" if isinstance(node, ast.If) else "while"
                test = node.test
                if _test_is_exempt(test):
                    continue
                used = {
                    n.id for n in ast.walk(test) if isinstance(n, ast.Name)
                } & arrayish
                if used:
                    problems.append(
                        self.finding(
                            unit.rel,
                            node.lineno,
                            "traced-branch",
                            f"{qual}:{kind}:{'/'.join(sorted(used))}",
                            f"Python `{kind}` on {sorted(used)} inside traced "
                            f"function `{qual}` — a traced value here raises "
                            "under jit or forces a host sync; use "
                            "`jax.lax.cond`/`where` (or mark the value "
                            f"static){via}",
                            severity="warning",
                        )
                    )
        return problems

    @staticmethod
    def _arg_is_arrayish(unit: ModuleUnit, arg: ast.AST, arrayish: Set[str]) -> bool:
        """The cast argument plausibly holds a traced value: it mentions an
        arrayish name or contains a device-namespace call."""
        for n in ast.walk(arg):
            if isinstance(n, ast.Name) and n.id in arrayish:
                return True
            if isinstance(n, ast.Call):
                resolved = unit.resolve(n.func)
                if resolved and resolved.startswith(DEVICE_NAMESPACES):
                    return True
        return False

    def _check_call(
        self, unit: ModuleUnit, qual: str, node: ast.Call, arrayish: Set[str], via: str
    ) -> List[Finding]:
        out: List[Finding] = []
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in HOST_PULL_CALLS:
            out.append(
                self.finding(
                    unit.rel,
                    node.lineno,
                    "host-pull",
                    f"{qual}:{fn.attr}",
                    f"`.{fn.attr}()` inside traced function `{qual}` forces a "
                    f"device->host round-trip (and raises under jit){via}",
                )
            )
        elif (
            isinstance(fn, ast.Name)
            and fn.id in HOST_CASTS
            and fn.id not in unit.imports  # a local alias shadows the builtin
            and len(node.args) == 1
            and not node.keywords
            and not isinstance(node.args[0], ast.Constant)
            and not _is_static_read(node.args[0])
            and self._arg_is_arrayish(unit, node.args[0], arrayish)
        ):
            out.append(
                self.finding(
                    unit.rel,
                    node.lineno,
                    "host-cast",
                    f"{qual}:{fn.id}",
                    f"`{fn.id}(...)` of a non-static value inside traced "
                    f"function `{qual}` concretizes a tracer (raises under "
                    "jit); keep the value on device or read a static "
                    f"shape/dtype instead{via}",
                )
            )
        else:
            resolved = unit.resolve(fn)
            if resolved is not None:
                head, _, tail = resolved.rpartition(".")
                if head == "numpy" and tail in NUMPY_HOST_CALLS:
                    out.append(
                        self.finding(
                            unit.rel,
                            node.lineno,
                            "numpy-in-trace",
                            f"{qual}:numpy.{tail}",
                            f"host `numpy.{tail}` inside traced function "
                            f"`{qual}` pulls a traced array to the host; use "
                            "`jax.numpy` (check the import alias) or move the "
                            f"call out of the traced region{via}",
                        )
                    )
        return out
