"""ckpt-serializers: every registered state kind has a checkpoint serializer.

The checkpoint codec (``metrics_tpu/checkpoint/codec.py``) serializes metric
state BY KIND — the ``SERIALIZERS`` registry maps each kind reported by
``Metric.state_kinds()`` to its pack/unpack/merge path.  A new state
registration API (``add_*_state``) or a new kind that lands without a codec
entry would silently produce checkpoints that drop that state, or restores
that KeyError in production.  This dynamic pass pins the three surfaces
together:

1. every ``add*_state`` method on ``Metric`` appears in
   ``STATE_KIND_REGISTRARS`` (new registration APIs must declare their kinds);
2. every kind named by ``STATE_KIND_REGISTRARS`` has a ``SERIALIZERS`` entry;
3. every kind ``state_kinds()`` can emit — probed by instantiating one
   exemplar metric per kind — round-trips through ``encode_metric`` /
   ``decode_metric`` with digests verifying.

This pass is the ported ``tools/ckpt_lint.py`` (its module entry point
remains as a shim).  The state-contract pass reuses the coverage helpers
here for its serializer-coverage rule.
"""

from __future__ import annotations

import re
from typing import List, Tuple

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    register_pass,
)

_REGISTRAR_RE = re.compile(r"^add[a-z_]*_state$")
_CODEC_REL = "metrics_tpu/checkpoint/codec.py"


def coverage_problems() -> List[Tuple[str, str, str]]:
    """``(rule, detail, message)`` rows for registrar/serializer coverage."""
    from metrics_tpu.checkpoint.codec import META_STATE, SERIALIZERS, STATE_KIND_REGISTRARS
    from metrics_tpu.metric import Metric

    problems: List[Tuple[str, str, str]] = []

    # 1. every state-registration API on Metric is declared
    registrars = sorted(
        name
        for name in vars(Metric)
        if _REGISTRAR_RE.match(name) and callable(getattr(Metric, name))
    )
    for name in registrars:
        if name not in STATE_KIND_REGISTRARS:
            problems.append(
                (
                    "registrar-undeclared",
                    f"Metric.{name}",
                    f"Metric.{name}() registers state but is missing from "
                    "checkpoint.codec.STATE_KIND_REGISTRARS — declare which "
                    "codec kind(s) it produces so checkpoints cover it.",
                )
            )
    for name in STATE_KIND_REGISTRARS:
        if name not in registrars:
            problems.append(
                (
                    "registrar-stale",
                    f"Metric.{name}",
                    f"checkpoint.codec.STATE_KIND_REGISTRARS names {name!r} but "
                    "Metric has no such registration method — stale entry.",
                )
            )

    # 2. every declared kind has a serializer
    declared = {k for kinds in STATE_KIND_REGISTRARS.values() for k in kinds}
    for kind in sorted(declared):
        if kind not in SERIALIZERS:
            problems.append(
                (
                    "serializer-missing",
                    f"kind.{kind}",
                    f"state kind {kind!r} (declared in STATE_KIND_REGISTRARS) "
                    "has no checkpoint.codec.SERIALIZERS entry — it would be "
                    "dropped from every checkpoint.",
                )
            )
    for kind in SERIALIZERS:
        if kind != META_STATE and kind not in declared:
            problems.append(
                (
                    "serializer-stale",
                    f"kind.{kind}",
                    f"checkpoint.codec.SERIALIZERS entry {kind!r} is produced "
                    "by no registration API in STATE_KIND_REGISTRARS — stale "
                    "entry.",
                )
            )
    return problems


def roundtrip_problems() -> List[Tuple[str, str, str]]:
    """Probe one exemplar metric per kind through an encode/decode cycle."""
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.checkpoint.codec import decode_metric, encode_metric

    exemplars = {
        "tensor": (mt.MeanMetric(), lambda m: m.update(jnp.arange(4.0))),
        "list": (mt.CatMetric(), lambda m: m.update(jnp.arange(4.0))),
        "buffer": (
            mt.AUROC(),
            lambda m: m.update(
                jnp.asarray([0.1, 0.8, 0.4, 0.9]), jnp.asarray([0, 1, 0, 1])
            ),
        ),
        "sketch": (mt.StreamingQuantile(), lambda m: m.update(jnp.arange(32.0))),
    }
    problems: List[Tuple[str, str, str]] = []
    for kind, (metric, feed) in exemplars.items():
        feed(metric)
        kinds = set(metric.state_kinds().values())
        if kind not in kinds:
            problems.append(
                (
                    "roundtrip-exemplar",
                    f"kind.{kind}",
                    f"exemplar for kind {kind!r} ({type(metric).__name__}) "
                    f"reports kinds {sorted(kinds)} — update the exemplar "
                    "table.",
                )
            )
            continue
        enc = encode_metric(metric)
        dec = decode_metric(enc.blob, enc.digests)
        if dec.failed:
            problems.append(
                (
                    "roundtrip-failed",
                    f"kind.{kind}",
                    f"kind {kind!r} ({type(metric).__name__}) failed its own "
                    f"encode/decode round trip: state(s) {dec.failed} did not "
                    "verify.",
                )
            )
        missing = set(enc.digests) - set(dec.arrays) - set(dec.failed)
        if missing:
            problems.append(
                (
                    "roundtrip-lost",
                    f"kind.{kind}",
                    f"kind {kind!r} round trip silently lost state(s) "
                    f"{sorted(missing)}.",
                )
            )
    return problems


@register_pass
class CkptSerializersPass(AnalysisPass):
    name = "ckpt-serializers"
    description = (
        "every Metric state kind is declared to the checkpoint codec, has a "
        "serializer, and round-trips encode/decode with digests verifying"
    )
    kind = "dynamic"

    def check_package(self, ctx: AnalysisContext) -> List[Finding]:
        return [
            self.finding(_CODEC_REL, 0, rule, detail, message)
            for rule, detail, message in coverage_problems() + roundtrip_problems()
        ]
