"""obs-instrumentation: every Metric subclass stays on the instrumented path.

The obs spans/counters in ``metrics_tpu.obs`` are attached once, in
``Metric._update_wrapper`` / ``Metric._compute_wrapper`` / ``Metric.sync`` /
``Metric._finish_sync_report``.  A subclass that shadows one of those in its
class dict silently drops out of the telemetry (no update/compute spans, no
sync report recording) — which is exactly the kind of regression that never
shows up in functional tests.  This dynamic pass imports ``metrics_tpu``,
walks the full ``Metric`` subclass tree, and reports any first-party
subclass that overrides an instrumented method without being allowlisted.

This pass is the ported ``tools/obs_lint.py`` (its module entry point
remains as a shim).
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple, Type

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    register_pass,
)

# Methods that carry the instrumentation; overriding any of them in a class
# dict bypasses spans, recompile counters, or sync-report recording.
INSTRUMENTED_METHODS: Tuple[str, ...] = (
    "_update_wrapper",
    "_compute_wrapper",
    "_install_wrappers",
    "sync",
    "_finish_sync_report",
)

# (qualified class name) -> methods it may override.  CompositionalMetric
# re-dispatches through its operand metrics, each of which is spanned
# individually, so its wrapper overrides do not lose telemetry.
# MultiStreamMetric extends _finish_sync_report via super() to attribute
# stacked-state sync traffic to the multistream.sync_bytes counter — the
# base recording still runs first.
ALLOWLIST: Dict[str, Set[str]] = {
    "metrics_tpu.metric.CompositionalMetric": {"_update_wrapper", "_compute_wrapper"},
    "metrics_tpu.multistream.core.MultiStreamMetric": {"_finish_sync_report"},
}


def _walk_subclasses(cls: Type) -> List[Type]:
    out: List[Type] = []
    for sub in cls.__subclasses__():
        out.append(sub)
        out.extend(_walk_subclasses(sub))
    return out


def _module_rel(modname: str) -> str:
    return modname.replace(".", "/") + ".py"


@register_pass
class ObsInstrumentationPass(AnalysisPass):
    name = "obs-instrumentation"
    description = (
        "no Metric subclass shadows the instrumented base-class update/"
        "compute/sync wrappers"
    )
    kind = "dynamic"

    def check_package(self, ctx: AnalysisContext) -> List[Finding]:
        import metrics_tpu  # noqa: F401  (populates the subclass tree)
        from metrics_tpu.metric import Metric

        problems: List[Finding] = []
        seen: Set[Type] = set()
        for sub in _walk_subclasses(Metric):
            if sub in seen:
                continue
            seen.add(sub)
            if not sub.__module__.startswith("metrics_tpu"):
                continue  # user-defined subclasses are out of scope
            qualname = f"{sub.__module__}.{sub.__name__}"
            allowed = ALLOWLIST.get(qualname, set())
            for method in INSTRUMENTED_METHODS:
                if method in sub.__dict__ and method not in allowed:
                    problems.append(
                        self.finding(
                            _module_rel(sub.__module__),
                            0,
                            "shadowed-instrumentation",
                            f"{qualname}.{method}",
                            f"{qualname} overrides {method}(); it will bypass "
                            "obs instrumentation. Override update()/compute() "
                            "instead, or add an explicit allowlist entry in "
                            "tools/analyze/passes/obs_instrumentation.py.",
                        )
                    )
        return problems
