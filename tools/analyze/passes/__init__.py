"""Bundled analysis passes.

Importing this package registers every pass with the engine's global
registry (``tools.analyze.engine.PASSES``).  A new pass is one module here:
subclass :class:`tools.analyze.engine.AnalysisPass`, decorate with
``@register_pass``, and import it below.
"""

from tools.analyze.passes import (  # noqa: F401
    ckpt_serializers,
    lock_order,
    obs_instrumentation,
    serve_blocking,
    shape_static,
    state_contract,
    trace_safety,
)

# the dynamic runtime-sanitizer passes (lock-witness, state-race) live under
# tools.analyze.runtime with their instrumentation substrate
from tools.analyze import runtime  # noqa: E402,F401
