"""shape-static: fixed-shape subsystems never use data-dependent shapes.

The streaming, multistream, and serve subsystems' contract is fixed-shape
state: a jitted ``update`` must never recompile as the stream grows, sketch
states must pack into fixed-size sync blobs, ring buffers must rotate in
place, and stacked ``(num_streams, ...)`` states must scatter without
reshaping.  One stray ``jnp.nonzero`` / ``.item()`` / boolean-mask
extraction silently breaks that — it traces fine in eager tests and then
either crashes under jit or, worse, forces a retrace per batch.

Scope is the package walk restricted to the fixed-shape directories —
every module under ``metrics_tpu/streaming/``, ``metrics_tpu/multistream/``
and ``metrics_tpu/serve/`` is covered by default; a deliberately-eager
module opts out with ``# analyze: skip-file[shape-static] -- reason``.

This pass is the ported ``tools/shape_lint.py`` (its module entry point
remains as a shim).
"""

from __future__ import annotations

import ast
from typing import List

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    ModuleUnit,
    register_pass,
    walk_with_scope,
)

SCOPE_PREFIXES = (
    "metrics_tpu/streaming/",
    "metrics_tpu/multistream/",
    # the serving path dispatches compiled blocks: the same static-shape
    # discipline applies to everything between the queue and the metric
    "metrics_tpu/serve/",
    # the detection device kernels run over fixed-capacity padded operands;
    # the host-orchestration module opts out with a skip-file marker
    "metrics_tpu/detection/",
)

# call names whose result shape depends on data values
DYNAMIC_SHAPE_CALLS = {
    "nonzero",
    "flatnonzero",
    "argwhere",
    "unique",
    "unique_values",
    "extract",
    "compress",
    "setdiff1d",
    "union1d",
    "intersect1d",
}

# host-pull methods that would put a device sync inside state math
HOST_PULL_CALLS = {"item", "tolist"}


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


@register_pass
class ShapeStaticPass(AnalysisPass):
    name = "shape-static"
    description = (
        "streaming/multistream/serve state math stays fixed-shape: no "
        "data-dependent-shape ops, host pulls, or growing state kinds"
    )

    def applies(self, unit: ModuleUnit) -> bool:
        return unit.rel.startswith(SCOPE_PREFIXES)

    def check_module(self, unit: ModuleUnit, ctx: AnalysisContext) -> List[Finding]:
        problems: List[Finding] = []
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            where = scope or "<module>"
            if name in DYNAMIC_SHAPE_CALLS:
                problems.append(
                    self.finding(
                        unit.rel,
                        node.lineno,
                        "dynamic-shape",
                        f"{where}:{name}",
                        f"`{name}` produces a data-dependent shape; streaming "
                        "state must stay fixed-shape (mask with 3-arg `where` "
                        "instead)",
                    )
                )
            elif name == "where" and len(node.args) == 1 and not node.keywords:
                problems.append(
                    self.finding(
                        unit.rel,
                        node.lineno,
                        "where-indices",
                        f"{where}:where",
                        "single-argument `where` is data-dependent (returns "
                        "indices); use the 3-argument select form",
                    )
                )
            elif name in HOST_PULL_CALLS and isinstance(node.func, ast.Attribute):
                problems.append(
                    self.finding(
                        unit.rel,
                        node.lineno,
                        "host-pull",
                        f"{where}:{name}",
                        f"`.{name}()` forces a host round-trip inside streaming "
                        "code; keep state math on device",
                    )
                )
            elif name == "add_buffer_state":
                problems.append(
                    self.finding(
                        unit.rel,
                        node.lineno,
                        "buffer-state",
                        f"{where}:add_buffer_state",
                        "buffer states grow with the stream; streaming metrics "
                        "must use fixed-shape tensor or sketch states",
                    )
                )
            elif name == "add_state" and any(
                isinstance(a, ast.List) and not a.elts for a in node.args
            ):
                problems.append(
                    self.finding(
                        unit.rel,
                        node.lineno,
                        "list-state",
                        f"{where}:add_state",
                        "list-state default `[]` grows with the stream; "
                        "streaming metrics must use fixed-shape tensor or "
                        "sketch states",
                    )
                )
        return problems
