"""state-contract: every state registration is internally consistent.

``Metric.add_state`` / ``add_sketch_state`` / ``add_buffer_state`` calls
carry three contracts this pass checks at the call site, across the whole
package:

* **reduce/default consistency** (rule ``reduce-default``) — a state's
  default must be the identity of its ``dist_reduce_fx``: ``sum``/``mean``
  states must not default to ones/aranges/nonzero constants (double-counted
  on the first merge), ``max`` must not default to ``+inf`` and ``min`` to
  ``-inf`` (the reduction can never move off an absorbing default), and a
  list-state ``[]`` default only makes sense with ``cat`` gather semantics
  (rule ``list-state-reduce``).
* **sketch merge** (rule ``sketch-merge``) — ``add_sketch_state`` needs a
  real ``merge_fn`` callable, not a literal.
* **placement/reduce consistency** (rule ``spec-reduce``) — a sharded
  ``spec=PartitionSpec('batch')`` contradicts a scalar reduction
  (``sum``/``mean``/``max``/``min``): reduced states hold the full value on
  every device after sync and must replicate (``P()``); only gather-kind
  (``cat``/list/buffer) states shard their row axis.
* **stackability** (rule ``stackable-growing-state``) — a metric class
  annotated ``stackable = True`` (it promises to work as a
  ``MultiStreamMetric`` base, where every state gains a leading
  ``(num_streams, ...)`` axis) must not register list or buffer states:
  growing states have no fixed-shape per-stream stacked form, so the
  annotation and the registration contradict each other.
* **stackability coverage** (rule ``stackable-unannotated``) — every
  metric class under ``classification/`` and ``regression/`` that
  registers state must say whether it stacks: an explicit ``stackable =
  True/False`` in its own body or inherited from a package base (resolved
  through the call graph's class table).  Unannotated metrics silently
  fall back to ``None``, which ``MultiStreamMetric`` treats as "probably
  fine" — this rule turns that silence into a reviewed decision.
* **serializer coverage** (rules shared with ``ckpt-serializers``) — every
  registration API's kinds are declared to the checkpoint codec; this
  absorbs the old ``ckpt_lint`` static half so one pass owns the
  state-registration contract end to end.
"""

from __future__ import annotations

import ast
from typing import Any, List, Optional

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    ModuleUnit,
    register_pass,
)

_METRIC_REL = "metrics_tpu/metric.py"

# directories where every state-registering metric must carry (or inherit)
# an explicit stackable annotation
_ANNOTATED_DIRS = ("metrics_tpu/classification/", "metrics_tpu/regression/")
_STATE_REGISTRARS = {"add_state", "add_buffer_state", "add_sketch_state"}


def _class_stackable(node: ast.ClassDef) -> Optional[bool]:
    """The class body's own ``stackable`` annotation: True/False when it is
    an explicit bool constant, None when absent (or the base-class default
    ``stackable: Optional[bool] = None``, which is not an annotation)."""
    value: Any = None
    for stmt in node.body:
        if (
            isinstance(stmt, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "stackable" for t in stmt.targets
            )
            and isinstance(stmt.value, ast.Constant)
        ):
            value = stmt.value.value
        elif (
            isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
            and stmt.target.id == "stackable"
            and isinstance(stmt.value, ast.Constant)
        ):
            value = stmt.value.value
    return value if isinstance(value, bool) else None


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_inf(node: ast.AST, unit: ModuleUnit, sign: int) -> bool:
    """Whether ``node`` is statically ±inf (``jnp.inf``, ``float('inf')``,
    ``math.inf`` and friends), with ``sign`` +1 or -1."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _is_inf(node.operand, unit, -sign)
    if isinstance(node, ast.Call):
        fn = unit.resolve(node.func) or ""
        if fn in ("builtins.float", "float") and node.args:
            val = _const_str(node.args[0])
            if val is None:
                return False
            val = val.lower().lstrip("+")
            if val.startswith("-"):
                return sign < 0 and val[1:] in ("inf", "infinity")
            return sign > 0 and val in ("inf", "infinity")
        tail = fn.rsplit(".", 1)[-1]
        # full(shape, inf) / full_like(x, inf) / asarray(inf) / array(inf)
        if tail in ("full", "full_like") and len(node.args) >= 2:
            return _is_inf(node.args[1], unit, sign)
        if tail in ("asarray", "array") and node.args:
            return _is_inf(node.args[0], unit, sign)
    if sign < 0:
        return False
    resolved = unit.resolve(node)
    if resolved in ("jax.numpy.inf", "numpy.inf", "math.inf"):
        return True
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return node.value == float("inf")
    return False


def _spec_is_sharded(node: ast.AST, unit: ModuleUnit) -> bool:
    """Whether ``node`` is statically a PartitionSpec with a named axis
    (``P('batch')``, ``PartitionSpec(None, 'model')``, ...); a bare ``P()``
    or all-None spec replicates and is fine with any reduce."""
    if not isinstance(node, ast.Call):
        return False
    fn = (unit.resolve(node.func) or "").rsplit(".", 1)[-1] or (
        node.func.attr
        if isinstance(node.func, ast.Attribute)
        else getattr(node.func, "id", "")
    )
    if fn not in ("PartitionSpec", "P"):
        return False
    return any(
        not (isinstance(a, ast.Constant) and a.value is None) for a in node.args
    )


def _is_nonzero(node: ast.AST, unit: ModuleUnit) -> bool:
    """Whether ``node`` is statically a provably-nonzero default."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value != 0
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _is_nonzero(node.operand, unit)
    if isinstance(node, ast.Call):
        fn = (unit.resolve(node.func) or "").rsplit(".", 1)[-1]
        if fn in ("ones", "ones_like", "arange"):
            return True
        if fn in ("full", "full_like") and len(node.args) >= 2:
            return _is_nonzero(node.args[1], unit) or _is_inf(node.args[1], unit, 1) or _is_inf(
                node.args[1], unit, -1
            )
        if fn in ("asarray", "array") and node.args:
            return _is_nonzero(node.args[0], unit)
    return False


@register_pass
class StateContractPass(AnalysisPass):
    name = "state-contract"
    description = (
        "add_state/add_sketch_state registrations keep dist_reduce kind, "
        "default value, stackability annotation, and checkpoint-serializer "
        "coverage consistent"
    )

    # ------------------------------------------------------------ per module
    def check_module(self, unit: ModuleUnit, ctx: AnalysisContext) -> List[Finding]:
        problems: List[Finding] = []
        self._check_calls(unit, problems)
        self._check_stackability(unit, problems)
        return problems

    def _check_calls(self, unit: ModuleUnit, problems: List[Finding]) -> None:
        from tools.analyze.engine import walk_with_scope

        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            where = scope or "<module>"
            if name == "add_state":
                self._check_add_state(unit, node, where, problems)
            elif name == "add_sketch_state":
                merge = node.args[2] if len(node.args) > 2 else _kwarg(node, "merge_fn")
                if isinstance(merge, ast.Constant):
                    problems.append(
                        self.finding(
                            unit.rel,
                            node.lineno,
                            "sketch-merge",
                            f"{where}:add_sketch_state",
                            "`add_sketch_state` needs a callable `merge_fn` "
                            f"(got the literal {merge.value!r}); sketch sync "
                            "folds gathered trees through it",
                        )
                    )

    def _check_add_state(
        self, unit: ModuleUnit, node: ast.Call, where: str, problems: List[Finding]
    ) -> None:
        if len(node.args) < 2:
            return
        state_name = _const_str(node.args[0]) or "<dynamic>"
        default = node.args[1]
        reduce_node = node.args[2] if len(node.args) > 2 else _kwarg(node, "dist_reduce_fx")
        reduce_fx = _const_str(reduce_node)
        detail = f"{where}:{state_name}"
        spec_node = _kwarg(node, "spec")
        if (
            spec_node is not None
            and reduce_fx in ("sum", "mean", "max", "min")
            and _spec_is_sharded(spec_node, unit)
        ):
            problems.append(
                self.finding(
                    unit.rel,
                    node.lineno,
                    "spec-reduce",
                    detail,
                    f"state {state_name!r} declares a sharded spec= placement "
                    f"but reduces with {reduce_fx!r} — reduced states hold the "
                    "full value on every device after sync and must replicate "
                    "(P()); only cat/list/buffer states shard their row axis",
                )
            )
        if isinstance(default, ast.List) and not default.elts:
            if reduce_fx is not None and reduce_fx != "cat":
                problems.append(
                    self.finding(
                        unit.rel,
                        node.lineno,
                        "list-state-reduce",
                        detail,
                        f"list state {state_name!r} defaults to `[]` (gathered "
                        f"with cat semantics) but declares dist_reduce_fx="
                        f"{reduce_fx!r} — rows would be reduced, not "
                        "concatenated",
                    )
                )
            return
        if reduce_fx in ("sum", "mean") and _is_nonzero(default, unit):
            problems.append(
                self.finding(
                    unit.rel,
                    node.lineno,
                    "reduce-default",
                    detail,
                    f"state {state_name!r} declares dist_reduce_fx="
                    f"{reduce_fx!r} but its default is provably nonzero — the "
                    "default is folded into every merge/sync, double-counting "
                    "from the first reduction",
                )
            )
        elif reduce_fx == "max" and _is_inf(default, unit, +1):
            problems.append(
                self.finding(
                    unit.rel,
                    node.lineno,
                    "reduce-default",
                    detail,
                    f"state {state_name!r} reduces with `max` but defaults to "
                    "+inf — an absorbing default: the state can never move, "
                    "use -inf (the max identity)",
                )
            )
        elif reduce_fx == "min" and _is_inf(default, unit, -1):
            problems.append(
                self.finding(
                    unit.rel,
                    node.lineno,
                    "reduce-default",
                    detail,
                    f"state {state_name!r} reduces with `min` but defaults to "
                    "-inf — an absorbing default: the state can never move, "
                    "use +inf (the min identity)",
                )
            )

    # --------------------------------------------------------- stackability
    def _check_stackability(self, unit: ModuleUnit, problems: List[Finding]) -> None:
        for node in ast.walk(unit.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if _class_stackable(node) is not True:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                cname = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else ""
                )
                growing = cname == "add_buffer_state" or (
                    cname == "add_state"
                    and len(sub.args) >= 2
                    and isinstance(sub.args[1], ast.List)
                    and not sub.args[1].elts
                )
                if growing:
                    problems.append(
                        self.finding(
                            unit.rel,
                            sub.lineno,
                            "stackable-growing-state",
                            f"{node.name}:{cname}",
                            f"class {node.name} declares `stackable = True` "
                            "(usable as a MultiStreamMetric base) but registers "
                            f"a growing state via `{cname}` — growing states "
                            "have no fixed-shape (num_streams, ...) stacked "
                            "form; use tensor or sketch states, or drop the "
                            "annotation",
                        )
                    )

    # -------------------------------------------- stackability annotation
    def _unannotated_problems(self, ctx: AnalysisContext) -> List[Finding]:
        """``stackable-unannotated``: classification/regression metrics that
        register state must annotate stackability or inherit it."""
        from tools.analyze.callgraph import get_call_graph

        graph = get_call_graph(ctx)
        class_defs = {}  # dotted -> ClassDef, for base-body annotation lookup
        for unit in ctx.units:
            if unit.tree is None:
                continue
            for node in unit.tree.body:
                if isinstance(node, ast.ClassDef):
                    class_defs[f"{unit.dotted}.{node.name}"] = node

        def inherits_annotation(dotted: str, seen: set) -> bool:
            if dotted in seen:
                return False
            seen.add(dotted)
            info = graph.class_info(dotted)
            if info is None:
                return False
            for base in info.bases:
                base_def = class_defs.get(base)
                if base_def is not None and _class_stackable(base_def) is not None:
                    return True
                if inherits_annotation(base, seen):
                    return True
            return False

        problems: List[Finding] = []
        for unit in ctx.units:
            if unit.tree is None or not unit.rel.startswith(_ANNOTATED_DIRS):
                continue
            for node in unit.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                registers = any(
                    isinstance(sub, ast.Call)
                    and (
                        sub.func.attr
                        if isinstance(sub.func, ast.Attribute)
                        else getattr(sub.func, "id", "")
                    )
                    in _STATE_REGISTRARS
                    for sub in ast.walk(node)
                )
                if not registers or _class_stackable(node) is not None:
                    continue
                if inherits_annotation(f"{unit.dotted}.{node.name}", set()):
                    continue
                problems.append(
                    self.finding(
                        unit.rel,
                        node.lineno,
                        "stackable-unannotated",
                        f"{node.name}:stackable",
                        f"metric class {node.name} registers state but neither "
                        "declares `stackable` nor inherits an annotation — say "
                        "`stackable = True` (fixed-shape tensor/sketch states "
                        "only) or `stackable = False` (growing states) so "
                        "MultiStreamMetric eligibility is a reviewed decision, "
                        "not a default",
                        severity="warning",
                    )
                )
        return problems

    # ------------------------------------------------- serializer coverage
    def finish(self, ctx: AnalysisContext) -> List[Finding]:
        problems = self._unannotated_problems(ctx)
        if ctx.scratch.get("fixture_mode") or ctx.scratch.get("incremental_mode"):
            return problems  # fixture/--changed runs stay off the live codec
        from tools.analyze.passes.ckpt_serializers import coverage_problems

        try:
            rows = coverage_problems()
        except Exception as err:  # the package must import for this half
            return problems + [
                self.finding(
                    _METRIC_REL,
                    0,
                    "coverage-unavailable",
                    "import",
                    f"could not check serializer coverage: {type(err).__name__}: {err}",
                )
            ]
        return problems + [
            self.finding(_METRIC_REL, 0, rule, detail, message)
            for rule, detail, message in rows
        ]
