"""lock-order: consistent lock acquisition order, nothing blocks under a lock.

The threaded layers (serve's per-job ``RLock``s + ``registry.locked()``
sweep + the server's ``_ckpt_lock``, the checkpoint manager, obs' runtime
lock, the native-build lock) stay deadlock-free by two structural rules
this pass checks statically — the static sibling of the PR-7 TOCTOU-hang
fix:

* **lock-order** — the static lock-acquisition graph (an edge A -> B when B
  is acquired while A is held, aggregated across the whole package) must
  not contain a 2-cycle: if one code path takes A then B and another takes
  B then A, two threads can deadlock.  Lock identity is the normalized
  acquisition expression (``self.`` stripped), so ``job.lock`` in one
  module and ``self.lock`` in another unify per attribute path.
* **blocking-under-lock** — while any lock is held, no call may park the
  thread on something unbounded: collectives / barriers / KV waits /
  checkpoint commits (the serve-blocking vocabulary), untimed
  ``queue.put``/``queue.get`` (a dead consumer never drains the queue —
  exactly the PR-7 flush hang), zero-argument ``.wait()`` / ``.join()``,
  ``time.sleep``, and socket/HTTP reads.  One same-module call hop is
  followed: a call under a lock to a local function that itself blocks is
  flagged at the call site.

Deliberate quiesce points (the durability loop's save/restore under
``registry.locked()``, the soak harness' operator sync under a job lock)
are baselined with justifications rather than silenced in code.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    ModuleUnit,
    expr_text,
    register_pass,
)
from tools.analyze.passes.serve_blocking import BLOCKING_CALLS as COLLECTIVE_CALLS

# attribute reads that look like sockets/HTTP: parked on a peer
SOCKET_CALLS = {"urlopen", "recv", "accept", "connect", "sendall", "getresponse"}

_SCRATCH = "lock-order"


def _lock_id(expr: ast.AST) -> Optional[str]:
    """Normalized lock identity for a with-item / acquire receiver, or None."""
    if isinstance(expr, ast.Call):
        fn = expr.func
        if isinstance(fn, ast.Attribute) and fn.attr == "locked":
            base = expr_text(fn.value)
            return f"{_strip_self(base)}.locked()"
        return None
    text = None
    if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
        text = expr_text(expr)
    elif isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        text = expr.id
    return _strip_self(text) if text else None


def _strip_self(text: str) -> str:
    for prefix in ("self.", "cls."):
        if text.startswith(prefix):
            return text[len(prefix):]
    return text


def _receiver_is_queueish(expr: ast.AST) -> bool:
    last = expr_text(expr).split(".")[-1].lower()
    return last in ("q", "queue") or last.endswith("_q") or "queue" in last


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _blocking_reason(call: ast.Call, unit: ModuleUnit) -> Optional[str]:
    """Why this call can park the thread unboundedly, or None."""
    fn = call.func
    attr = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else ""
    )
    if attr in COLLECTIVE_CALLS:
        return f"`{attr}(...)` blocks on peers (collective/barrier/KV/commit)"
    if attr in SOCKET_CALLS:
        return f"`{attr}(...)` parks on a socket"
    if (
        attr in ("put", "get")
        and isinstance(fn, ast.Attribute)
        and _receiver_is_queueish(fn.value)
        and not _has_kwarg(call, "timeout")
        and not (attr == "get" and call.args)  # q.get(timeout) positional
    ):
        return (
            f"untimed `queue.{attr}` — a dead peer thread never drains/fills "
            "the queue"
        )
    if attr == "wait" and not call.args and not call.keywords:
        return "zero-argument `.wait()` parks forever if the event never fires"
    if attr == "join" and not call.args and not call.keywords and isinstance(fn, ast.Attribute):
        return "untimed `.join()` parks forever on a wedged thread"
    resolved = unit.resolve(fn)
    if resolved == "time.sleep":
        return "`time.sleep` stalls every waiter on the held lock"
    return None


class _FnScan:
    """Per-function results: findings plus call-graph hooks."""

    def __init__(self) -> None:
        self.direct_blocking: Optional[str] = None  # first blocking primitive
        self.calls_under_lock: List[Tuple[str, int, Tuple[str, ...]]] = []


@register_pass
class LockOrderPass(AnalysisPass):
    name = "lock-order"
    description = (
        "no inconsistent lock-acquisition order anywhere in the package, "
        "and nothing blocking (collective, untimed queue op, bare wait/join, "
        "sleep, socket) is called while a lock is held"
    )

    def applies(self, unit: ModuleUnit) -> bool:
        return "lock" in unit.source.lower()

    # ----------------------------------------------------------- per module
    def check_module(self, unit: ModuleUnit, ctx: AnalysisContext) -> List[Finding]:
        scratch = ctx.scratch.setdefault(
            _SCRATCH, {"edges": {}}
        )
        edges: Dict[Tuple[str, str], Tuple[str, int]] = scratch["edges"]
        problems: List[Finding] = []

        fns: List[Tuple[str, Optional[str], ast.AST]] = []

        def collect(node: ast.AST, scope: str, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{scope}.{child.name}" if scope else child.name
                    fns.append((qual, cls, child))
                    collect(child, qual, None)
                elif isinstance(child, ast.ClassDef):
                    qual = f"{scope}.{child.name}" if scope else child.name
                    collect(child, qual, qual)
                else:
                    collect(child, scope, cls)

        collect(unit.tree, "", None)
        by_simple: Dict[str, List[Tuple[str, Optional[str]]]] = {}
        for qual, cls, _node in fns:
            by_simple.setdefault(qual.rsplit(".", 1)[-1], []).append((qual, cls))

        scans: Dict[str, _FnScan] = {}
        for qual, cls, node in fns:
            scans[qual] = self._scan_function(unit, qual, cls, node, edges, problems)

        # one-hop propagation: a call under a lock to a local function that
        # itself blocks is a blocking call at the call site
        for qual, scan in scans.items():
            for callee_name, lineno, held in scan.calls_under_lock:
                caller_cls = next(c for q, c, _n in fns if q == qual)
                for callee_qual, callee_cls in by_simple.get(callee_name, []):
                    if callee_cls is not None and callee_cls != caller_cls:
                        continue
                    reason = scans[callee_qual].direct_blocking
                    if reason:
                        problems.append(
                            self.finding(
                                unit.rel,
                                lineno,
                                "blocking-callee-under-lock",
                                f"{qual}:{callee_name}",
                                f"`{callee_name}()` (which blocks: {reason}) is "
                                f"called while holding {list(held)}",
                            )
                        )
                        break
        return problems

    # --------------------------------------------------------- one function
    def _scan_function(
        self,
        unit: ModuleUnit,
        qual: str,
        cls: Optional[str],
        fn: ast.AST,
        edges: Dict[Tuple[str, str], Tuple[str, int]],
        problems: List[Finding],
    ) -> _FnScan:
        scan = _FnScan()

        def record_acquisition(lock: str, held: Tuple[str, ...], lineno: int) -> None:
            for h in held:
                if h != lock:
                    edges.setdefault((h, lock), (unit.rel, lineno))

        def check_call(call: ast.Call, held: Tuple[str, ...]) -> None:
            # standalone .acquire() is an acquisition event (release untracked)
            if isinstance(call.func, ast.Attribute) and call.func.attr == "acquire":
                lock = _lock_id(call.func.value)
                if lock:
                    record_acquisition(lock, held, call.lineno)
                    return
            reason = _blocking_reason(call, unit)
            if reason and scan.direct_blocking is None:
                scan.direct_blocking = reason
            if held:
                if reason:
                    attr = (
                        call.func.attr
                        if isinstance(call.func, ast.Attribute)
                        else expr_text(call.func)
                    )
                    problems.append(
                        self.finding(
                            unit.rel,
                            call.lineno,
                            "blocking-under-lock",
                            f"{qual}:{attr}",
                            f"{reason}; called while holding {list(held)} — "
                            "release the lock first or bound the wait",
                        )
                    )
                elif isinstance(call.func, ast.Name):
                    scan.calls_under_lock.append((call.func.id, call.lineno, held))
                elif isinstance(call.func, ast.Attribute) and isinstance(
                    call.func.value, ast.Name
                ) and call.func.value.id in ("self", "cls"):
                    scan.calls_under_lock.append((call.func.attr, call.lineno, held))

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                return  # nested defs are scanned as their own functions
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    lock = _lock_id(item.context_expr)
                    if lock:
                        record_acquisition(lock, new_held, item.context_expr.lineno)
                        new_held = new_held + (lock,)
                    else:
                        visit(item.context_expr, held)
                for stmt in node.body:
                    visit(stmt, new_held)
                return
            if isinstance(node, ast.Call):
                check_call(node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            visit(stmt, ())
        return scan

    # ------------------------------------------------------------ aggregate
    def finish(self, ctx: AnalysisContext) -> List[Finding]:
        scratch = ctx.scratch.get(_SCRATCH)
        if not scratch:
            return []
        edges: Dict[Tuple[str, str], Tuple[str, int]] = scratch["edges"]
        problems: List[Finding] = []
        for (a, b), (module, lineno) in sorted(edges.items()):
            if a < b and (b, a) in edges:
                other_mod, other_line = edges[(b, a)]
                problems.append(
                    self.finding(
                        module,
                        lineno,
                        "inconsistent-order",
                        f"{a}->{b}",
                        f"lock `{b}` is acquired while holding `{a}` here, but "
                        f"{other_mod}:{other_line} acquires `{a}` while holding "
                        f"`{b}` — two threads on these paths can deadlock; pick "
                        "one global order",
                    )
                )
        return problems
