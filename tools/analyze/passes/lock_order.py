"""lock-order: consistent lock acquisition order, nothing blocks under a lock.

The threaded layers (serve's per-job ``RLock``s + ``registry.locked()``
sweep + the server's ``_ckpt_lock``, the checkpoint manager, obs' runtime
lock, the native-build lock) stay deadlock-free by two structural rules
this pass checks statically — the static sibling of the PR-7 TOCTOU-hang
fix:

* **lock-order** — the static lock-acquisition graph (an edge A -> B when B
  is acquired while A is held, aggregated across the whole package) must
  not contain a 2-cycle: if one code path takes A then B and another takes
  B then A, two threads can deadlock.  Lock identity is the normalized
  acquisition expression (``self.`` stripped), so ``job.lock`` in one
  module and ``self.lock`` in another unify per attribute path.  Since the
  call-graph migration the edges are **transitive**: a function that calls
  (through any resolvable chain, bounded by :attr:`LockOrderPass.depth`)
  into a function that acquires a lock contributes the edge, with the full
  chain carried as provenance.
* **blocking-under-lock** — while any lock is held, no call may park the
  thread on something unbounded: collectives / barriers / KV waits /
  checkpoint commits (the serve-blocking vocabulary), untimed
  ``queue.put``/``queue.get`` (a dead consumer never drains the queue —
  exactly the PR-7 flush hang), zero-argument ``.wait()`` / ``.join()``,
  ``time.sleep``, and socket/HTTP reads.  Calls under a lock are closed
  over the whole-package call graph (``tools/analyze/callgraph.py``): a
  chain like ``EvalServer.checkpoint_now -> EvalServer.flush ->
  IngestQueue.put_control`` is followed to the blocking primitive and the
  finding prints the chain (rule ``blocking-callee-under-lock``).

Deliberate quiesce points (the durability loop's save/restore under
``registry.locked()``, the soak harness' operator sync under a job lock)
are baselined with justifications rather than silenced in code.  The
runtime sibling of this pass — ``lock-witness`` under
``tools/analyze/runtime/`` — wraps live locks and checks the same two
rules against what the serve suite actually does.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    ModuleUnit,
    expr_text,
    register_pass,
)
from tools.analyze.passes.serve_blocking import BLOCKING_CALLS as COLLECTIVE_CALLS

# attribute reads that look like sockets/HTTP: parked on a peer
SOCKET_CALLS = {"urlopen", "recv", "accept", "connect", "sendall", "getresponse"}

# the async-sync seam: these run (or synchronously wait on) whole collective
# rounds, so calling them with a lock held couples that lock to the
# background sync worker's progress — same hazard class as a raw collective.
# Kept local to this pass: serve_blocking's vocabulary is about the HTTP
# request path, which never touches the async seam.
ASYNC_COLLECTIVE_CALLS = {"guarded_collective", "sync_async", "_async_catchup"}

_SCRATCH = "lock-order"

# call edges followed from a lock-held call site before the search gives up
DEFAULT_DEPTH = 4


def _lock_id(expr: ast.AST) -> Optional[str]:
    """Normalized lock identity for a with-item / acquire receiver, or None."""
    if isinstance(expr, ast.Call):
        fn = expr.func
        if isinstance(fn, ast.Attribute) and fn.attr == "locked":
            base = expr_text(fn.value)
            return f"{_strip_self(base)}.locked()"
        return None
    text = None
    if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
        text = expr_text(expr)
    elif isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        text = expr.id
    return _strip_self(text) if text else None


def _strip_self(text: str) -> str:
    for prefix in ("self.", "cls."):
        if text.startswith(prefix):
            return text[len(prefix):]
    return text


def _receiver_is_queueish(expr: ast.AST) -> bool:
    last = expr_text(expr).split(".")[-1].lower()
    return last in ("q", "queue") or last.endswith("_q") or "queue" in last


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _blocking_reason(call: ast.Call, unit: ModuleUnit) -> Optional[str]:
    """Why this call can park the thread unboundedly, or None."""
    fn = call.func
    attr = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else ""
    )
    if attr in COLLECTIVE_CALLS:
        return f"`{attr}(...)` blocks on peers (collective/barrier/KV/commit)"
    if attr in ASYNC_COLLECTIVE_CALLS:
        return (
            f"`{attr}(...)` runs or awaits a sync round on the background "
            "sync worker"
        )
    if attr in SOCKET_CALLS:
        return f"`{attr}(...)` parks on a socket"
    if (
        attr in ("put", "get")
        and isinstance(fn, ast.Attribute)
        and _receiver_is_queueish(fn.value)
        and not _has_kwarg(call, "timeout")
        and not (attr == "get" and call.args)  # q.get(timeout) positional
    ):
        return (
            f"untimed `queue.{attr}` — a dead peer thread never drains/fills "
            "the queue"
        )
    if attr == "wait" and not call.args and not call.keywords:
        return "zero-argument `.wait()` parks forever if the event never fires"
    if attr == "join" and not call.args and not call.keywords and isinstance(fn, ast.Attribute):
        return "untimed `.join()` parks forever on a wedged thread"
    resolved = unit.resolve(fn)
    if resolved == "time.sleep":
        return "`time.sleep` stalls every waiter on the held lock"
    return None


class _FnScan:
    """Per-function facts the cross-module closure consumes."""

    __slots__ = ("direct_blocking", "acquisitions", "held_calls")

    def __init__(self) -> None:
        self.direct_blocking: Optional[Tuple[str, int]] = None  # (reason, line)
        # every acquisition event in this function: (lock, lineno)
        self.acquisitions: List[Tuple[str, int]] = []
        # call sites executed while holding locks: lineno -> held tuple
        self.held_calls: Dict[int, Tuple[str, ...]] = {}


@register_pass
class LockOrderPass(AnalysisPass):
    name = "lock-order"
    description = (
        "no inconsistent lock-acquisition order anywhere in the package, "
        "and nothing blocking (collective, untimed queue op, bare wait/join, "
        "sleep, socket) is reachable through the call graph while a lock is "
        "held"
    )

    def __init__(self) -> None:
        self.depth = DEFAULT_DEPTH

    def applies(self, unit: ModuleUnit) -> bool:
        return "lock" in unit.source.lower()

    # ----------------------------------------------------------- per module
    def check_module(self, unit: ModuleUnit, ctx: AnalysisContext) -> List[Finding]:
        from tools.analyze.callgraph import collect_functions

        scratch = ctx.scratch.setdefault(_SCRATCH, {"edges": {}, "scans": {}})
        problems: List[Finding] = []
        funcs, _classes = collect_functions(unit.tree, unit.rel)
        for f in funcs:
            scan = self._scan_function(unit, f.qualname, f.node, scratch["edges"], problems)
            scratch["scans"][f.fid] = scan
        return problems

    # --------------------------------------------------------- one function
    def _scan_function(
        self,
        unit: ModuleUnit,
        qual: str,
        fn: ast.AST,
        edges: Dict[Tuple[str, str], Tuple[str, int, Optional[str]]],
        problems: List[Finding],
    ) -> _FnScan:
        scan = _FnScan()

        def record_acquisition(lock: str, held: Tuple[str, ...], lineno: int) -> None:
            scan.acquisitions.append((lock, lineno))
            for h in held:
                if h != lock:
                    edges.setdefault((h, lock), (unit.rel, lineno, None))

        def check_call(call: ast.Call, held: Tuple[str, ...]) -> None:
            # standalone .acquire() is an acquisition event (release untracked)
            if isinstance(call.func, ast.Attribute) and call.func.attr == "acquire":
                lock = _lock_id(call.func.value)
                if lock:
                    record_acquisition(lock, held, call.lineno)
                    return
            reason = _blocking_reason(call, unit)
            if reason and scan.direct_blocking is None:
                scan.direct_blocking = (reason, call.lineno)
            if held:
                if reason:
                    attr = (
                        call.func.attr
                        if isinstance(call.func, ast.Attribute)
                        else expr_text(call.func)
                    )
                    problems.append(
                        self.finding(
                            unit.rel,
                            call.lineno,
                            "blocking-under-lock",
                            f"{qual}:{attr}",
                            f"{reason}; called while holding {list(held)} — "
                            "release the lock first or bound the wait",
                        )
                    )
                else:
                    # the call-graph closure (finish) follows this site
                    scan.held_calls.setdefault(call.lineno, held)

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                return  # nested defs are scanned as their own functions
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    lock = _lock_id(item.context_expr)
                    if lock:
                        record_acquisition(lock, new_held, item.context_expr.lineno)
                        new_held = new_held + (lock,)
                    else:
                        visit(item.context_expr, held)
                for stmt in node.body:
                    visit(stmt, new_held)
                return
            if isinstance(node, ast.Call):
                check_call(node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            visit(stmt, ())
        return scan

    # ----------------------------------------------- lazy cross-module scans
    def _scan_for(self, fid: str, ctx: AnalysisContext) -> Optional[_FnScan]:
        """The scan for a reached function, scanning on demand when its
        module was prefiltered out by ``applies`` (no "lock" in source)."""
        from tools.analyze.callgraph import get_call_graph

        scratch = ctx.scratch.setdefault(_SCRATCH, {"edges": {}, "scans": {}})
        scans: Dict[str, _FnScan] = scratch["scans"]
        if fid in scans:
            return scans[fid]
        graph = get_call_graph(ctx)
        node = graph.node(fid)
        if node is None:
            return None
        unit = ctx.unit(node.rel)
        if unit is None or unit.tree is None:
            return None
        # a module without "lock" in its source cannot hold locks, so the
        # direct problems list is always empty here — discard it
        scan = self._scan_function(unit, node.qualname, node.node, scratch["edges"], [])
        scans[fid] = scan
        return scan

    # ------------------------------------------------------------ aggregate
    def finish(self, ctx: AnalysisContext) -> List[Finding]:
        from tools.analyze.callgraph import get_call_graph

        scratch = ctx.scratch.get(_SCRATCH)
        if not scratch:
            return []
        graph = get_call_graph(ctx)
        edges: Dict[Tuple[str, str], Tuple[str, int, Optional[str]]] = scratch["edges"]
        problems: List[Finding] = []

        # close every lock-held call site over the call graph
        for fid, scan in sorted(scratch["scans"].items()):
            if not scan.held_calls:
                continue
            caller = graph.node(fid)
            if caller is None:
                continue
            for lineno, held in sorted(scan.held_calls.items()):
                starts = [
                    (e.callee, e.lineno)
                    for e in graph.out.get(fid, ())
                    if e.lineno == lineno
                ]
                if not starts:
                    continue
                reached = graph.chains(starts, depth=self.depth - 1)
                blocking: List[Tuple[int, str, List[Tuple[str, int]], str]] = []
                for callee_fid, chain in reached.items():
                    callee_scan = self._scan_for(callee_fid, ctx)
                    if callee_scan is None:
                        continue
                    if callee_scan.direct_blocking is not None:
                        blocking.append(
                            (len(chain), callee_fid, chain, callee_scan.direct_blocking[0])
                        )
                    # transitive acquisition: every lock taken down the chain
                    # is taken while the caller's held set is still held
                    for lock, acq_lineno in callee_scan.acquisitions:
                        chain_str = (
                            f"{caller.qualname} -> {graph.render_chain(chain)}"
                        )
                        for h in held:
                            if h != lock:
                                edges.setdefault(
                                    (h, lock), (caller.rel, lineno, chain_str)
                                )
                # one finding per call site: the shortest chain to a blocker
                if blocking:
                    blocking.sort(key=lambda item: (item[0], item[1]))
                    _, callee_fid, chain, reason = blocking[0]
                    chain_quals = [graph.display(c) for c, _ in chain]
                    problems.append(
                        self.finding(
                            caller.rel,
                            lineno,
                            "blocking-callee-under-lock",
                            f"{caller.qualname}:{'->'.join(chain_quals)}",
                            f"`{chain_quals[-1]}()` blocks ({reason}); reached "
                            f"via {caller.qualname} -> "
                            f"{' -> '.join(chain_quals)} while holding "
                            f"{list(held)}",
                        )
                    )

        # 2-cycles in the aggregated acquisition graph
        for (a, b), (module, lineno, chain) in sorted(edges.items()):
            if a < b and (b, a) in edges:
                other_mod, other_line, other_chain = edges[(b, a)]
                here = f"here ({chain})" if chain else "here"
                there = (
                    f"{other_mod}:{other_line}"
                    + (f" ({other_chain})" if other_chain else "")
                )
                problems.append(
                    self.finding(
                        module,
                        lineno,
                        "inconsistent-order",
                        f"{a}->{b}",
                        f"lock `{b}` is acquired while holding `{a}` {here}, "
                        f"but {there} acquires `{a}` while holding `{b}` — "
                        "two threads on these paths can deadlock; pick one "
                        "global order",
                    )
                )
        return problems
