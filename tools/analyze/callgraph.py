"""Whole-package call graph: the interprocedural substrate for the passes.

One graph per engine run (cached in ``AnalysisContext.scratch``), built from
the same :class:`~tools.analyze.engine.ModuleUnit` table every pass sees.
Nodes are functions keyed ``<module rel>::<qualname>``; edges are resolved
call sites carrying their source line, so a pass can print the full chain
behind a transitive finding (``EvalServer.flush -> IngestQueue.put_control``)
instead of a bare call-site location.

Edge resolution is deliberately conservative — an edge exists only when the
static evidence names a concrete target:

* **local calls** — ``helper(...)`` binds to same-module functions with that
  simple name, or (through the import table) to a function in another
  package module (``from metrics_tpu.serve.ingest import chunks``).
* **self/cls methods** — ``self.flush()`` binds to the enclosing class's
  method, walking resolved base classes across modules when the class does
  not define it.
* **typed receivers** — ``self.queue.put_control()`` binds through the
  class's attribute-type table: an attribute assigned a constructor call in
  any method (``self.queue = IngestQueue(...)``) carries that class's type.
  Local names get the same treatment (``mgr = CheckpointManager(...)``).
* **class-qualified calls** — ``IngestQueue.put_control(q, ...)`` binds
  through the import table to the named class's method.

Receiver typing stops at constructor assignments on purpose: propagating
parameter annotations or return types would flood the graph with
possible-but-unproven edges, and every downstream pass treats an edge as
"this call CAN happen" when deciding to report.

Reachability helpers (:meth:`CallGraph.chains`) are depth-bounded BFS that
return the shortest provenance chain per reached node — the passes bound
their closure (``depth`` attribute on each migrated pass) so a pathological
fixture cannot make analysis super-linear.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analyze.engine import AnalysisContext, ModuleUnit, dotted_name

_SCRATCH_KEY = "callgraph"

PACKAGE_PREFIX = "metrics_tpu."


@dataclasses.dataclass
class FuncNode:
    """One function/method/lambda in the package."""

    fid: str  # "<module rel>::<qualname>"
    rel: str
    qualname: str
    cls: Optional[str]  # enclosing class qualname (module-local) or None
    node: ast.AST
    lineno: int

    @property
    def simple(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


@dataclasses.dataclass(frozen=True)
class CallEdge:
    """One resolved call site: ``caller`` invokes ``callee`` at ``lineno``."""

    caller: str
    callee: str
    lineno: int


@dataclasses.dataclass
class ClassInfo:
    """One class: its methods by simple name, resolved bases, attr types."""

    dotted: str  # "<module dotted>.<class qualname>"
    rel: str
    methods: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    bases: List[str] = dataclasses.field(default_factory=list)  # resolved dotted
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)


def body_nodes(fn: ast.AST):
    """Walk a function's own body without descending into nested defs."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
            ):
                continue
            stack.append(child)


def collect_functions(tree: ast.Module, rel: str) -> Tuple[List[FuncNode], List[Tuple[str, ast.ClassDef]]]:
    """All functions (incl. lambdas) plus the class definitions of a module."""
    funcs: List[FuncNode] = []
    classes: List[Tuple[str, ast.ClassDef]] = []

    def visit(node: ast.AST, scope: str, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{scope}.{child.name}" if scope else child.name
                funcs.append(FuncNode(f"{rel}::{qual}", rel, qual, cls, child, child.lineno))
                visit(child, qual, None)
            elif isinstance(child, ast.ClassDef):
                qual = f"{scope}.{child.name}" if scope else child.name
                classes.append((qual, child))
                visit(child, qual, qual)
            elif isinstance(child, ast.Lambda):
                qual = (
                    f"{scope}.<lambda@{child.lineno}>" if scope else f"<lambda@{child.lineno}>"
                )
                funcs.append(FuncNode(f"{rel}::{qual}", rel, qual, cls, child, child.lineno))
                visit(child, qual, None)
            else:
                visit(child, scope, cls)

    visit(tree, "", None)
    return funcs, classes


class CallGraph:
    """The package-wide graph plus the lookup tables edge resolution used."""

    def __init__(self) -> None:
        self.funcs: Dict[str, FuncNode] = {}
        self.out: Dict[str, List[CallEdge]] = {}
        self.classes: Dict[str, ClassInfo] = {}  # dotted class -> info
        self._by_module_simple: Dict[Tuple[str, str], List[str]] = {}
        self._func_by_dotted: Dict[str, List[str]] = {}
        self._class_by_rel_qual: Dict[Tuple[str, str], ClassInfo] = {}
        # module dependency edges (rel -> rels it statically calls/imports into)
        self.module_deps: Dict[str, Set[str]] = {}

    # ------------------------------------------------------------- lookups
    def node(self, fid: str) -> Optional[FuncNode]:
        return self.funcs.get(fid)

    def local_candidates(self, rel: str, simple: str) -> List[str]:
        return self._by_module_simple.get((rel, simple), [])

    def function_by_dotted(self, dotted: str) -> List[str]:
        return self._func_by_dotted.get(dotted, [])

    def class_info(self, dotted: str) -> Optional[ClassInfo]:
        return self.classes.get(dotted)

    def resolve_method(
        self, class_dotted: str, simple: str, _seen: Optional[Set[str]] = None
    ) -> List[str]:
        """Method lookup on a class, walking resolved bases (C3-ish, first hit)."""
        seen = _seen if _seen is not None else set()
        if class_dotted in seen:
            return []
        seen.add(class_dotted)
        info = self.classes.get(class_dotted)
        if info is None:
            return []
        hit = info.methods.get(simple)
        if hit:
            return hit
        for base in info.bases:
            found = self.resolve_method(base, simple, seen)
            if found:
                return found
        return []

    def display(self, fid: str) -> str:
        """Human chain segment: the qualname (module added only on clashes)."""
        node = self.funcs.get(fid)
        return node.qualname if node is not None else fid

    # -------------------------------------------------------- reachability
    def chains(
        self,
        starts: Sequence[Tuple[str, int]],
        depth: int,
        stop: Optional[Callable[[FuncNode], bool]] = None,
    ) -> Dict[str, List[Tuple[str, int]]]:
        """Depth-bounded BFS from ``starts`` (``(fid, call lineno)`` pairs).

        Returns ``{reached fid: [(fid, lineno), ...]}`` — the shortest chain
        of call sites from a start to that node, starts included.  ``stop``
        prunes expansion below matching nodes (their chain is still
        recorded), which is how a pass stops at the first blocking callee.
        """
        reached: Dict[str, List[Tuple[str, int]]] = {}
        frontier: List[Tuple[str, List[Tuple[str, int]]]] = []
        for fid, lineno in starts:
            if fid in self.funcs and fid not in reached:
                chain = [(fid, lineno)]
                reached[fid] = chain
                frontier.append((fid, chain))
        for _ in range(max(0, depth)):
            if not frontier:
                break
            nxt: List[Tuple[str, List[Tuple[str, int]]]] = []
            for fid, chain in frontier:
                node = self.funcs[fid]
                if stop is not None and stop(node):
                    continue
                for edge in self.out.get(fid, ()):
                    if edge.callee in reached:
                        continue
                    sub = chain + [(edge.callee, edge.lineno)]
                    reached[edge.callee] = sub
                    nxt.append((edge.callee, sub))
            frontier = nxt
        return reached

    def render_chain(self, chain: Sequence[Tuple[str, int]]) -> str:
        return " -> ".join(self.display(fid) for fid, _ in chain)

    # ---------------------------------------------------------- dependents
    def dependents(self, rels: Iterable[str]) -> Set[str]:
        """Transitive reverse module-dependency closure of ``rels``."""
        reverse: Dict[str, Set[str]] = {}
        for src, dsts in self.module_deps.items():
            for dst in dsts:
                reverse.setdefault(dst, set()).add(src)
        out: Set[str] = set()
        frontier = [r for r in rels]
        while frontier:
            rel = frontier.pop()
            for dep in reverse.get(rel, ()):
                if dep not in out:
                    out.add(dep)
                    frontier.append(dep)
        return out


# ---------------------------------------------------------------------------
# construction


def _resolve_class_ref(unit: ModuleUnit, expr: ast.AST, local_classes: Set[str]) -> Optional[str]:
    """A Name/Attribute that names a class -> its dotted package path."""
    dotted = dotted_name(expr)
    if dotted is None:
        return None
    if dotted in local_classes:
        return f"{unit.dotted}.{dotted}"
    resolved = unit.resolve(expr)
    if resolved and resolved.startswith(PACKAGE_PREFIX):
        return resolved
    return None


def _constructor_class(
    unit: ModuleUnit, value: ast.AST, local_classes: Set[str]
) -> Optional[str]:
    """``IngestQueue(...)`` -> the constructed class's dotted path, or None."""
    if not isinstance(value, ast.Call):
        return None
    return _resolve_class_ref(unit, value.func, local_classes)


def build_call_graph(units: Sequence[ModuleUnit]) -> CallGraph:
    graph = CallGraph()
    per_unit: List[Tuple[ModuleUnit, List[FuncNode], List[Tuple[str, ast.ClassDef]]]] = []
    dotted_to_rel: Dict[str, str] = {}

    # pass 1: nodes, class tables, module index
    for unit in units:
        tree = unit.tree
        if tree is None:
            continue
        dotted_to_rel[unit.dotted] = unit.rel
        funcs, classes = collect_functions(tree, unit.rel)
        per_unit.append((unit, funcs, classes))
        for f in funcs:
            graph.funcs[f.fid] = f
            graph._by_module_simple.setdefault((unit.rel, f.simple), []).append(f.fid)
            graph._func_by_dotted.setdefault(f"{unit.dotted}.{f.qualname}", []).append(f.fid)
        local_class_names = {qual for qual, _ in classes}
        for qual, cls_node in classes:
            info = ClassInfo(dotted=f"{unit.dotted}.{qual}", rel=unit.rel)
            for f in funcs:
                if f.cls == qual:
                    info.methods.setdefault(f.simple, []).append(f.fid)
            for base in cls_node.bases:
                resolved = _resolve_class_ref(unit, base, local_class_names)
                if resolved:
                    info.bases.append(resolved)
            graph.classes[info.dotted] = info
            graph._class_by_rel_qual[(unit.rel, qual)] = info

    # pass 2: attribute types (self.x = Class(...)) — needs the class table
    for unit, funcs, classes in per_unit:
        local_class_names = {qual for qual, _ in classes}
        for f in funcs:
            if f.cls is None:
                continue
            info = graph._class_by_rel_qual.get((unit.rel, f.cls))
            if info is None:
                continue
            for node in body_nodes(f.node):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                if value is None:
                    continue
                ctor = _constructor_class(unit, value, local_class_names)
                if ctor is None:
                    continue
                for target in targets:
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id in ("self", "cls")
                    ):
                        info.attr_types.setdefault(target.attr, ctor)

    # pass 3: edges
    for unit, funcs, classes in per_unit:
        local_class_names = {qual for qual, _ in classes}
        deps = graph.module_deps.setdefault(unit.rel, set())
        # package-internal imports are module dependencies even without a
        # resolved call edge (constants, class references, decorators)
        for alias_target in unit.imports.values():
            if not alias_target.startswith(PACKAGE_PREFIX) and alias_target != "metrics_tpu":
                continue
            probe = alias_target
            while probe and probe not in dotted_to_rel:
                probe = probe.rpartition(".")[0]
            if probe:
                deps.add(dotted_to_rel[probe])
        for f in funcs:
            # local variable constructor types within this function
            local_types: Dict[str, str] = {}
            for node in body_nodes(f.node):
                targets = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                if value is None:
                    continue
                ctor = _constructor_class(unit, value, local_class_names)
                if ctor is None:
                    continue
                for target in targets:
                    if isinstance(target, ast.Name):
                        local_types.setdefault(target.id, ctor)
            seen_edges: Set[Tuple[str, int]] = set()
            for node in body_nodes(f.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in _resolve_call(graph, unit, f, node, local_types, local_class_names):
                    key = (callee, node.lineno)
                    if key in seen_edges:
                        continue
                    seen_edges.add(key)
                    graph.out.setdefault(f.fid, []).append(
                        CallEdge(caller=f.fid, callee=callee, lineno=node.lineno)
                    )
                    target_rel = graph.funcs[callee].rel
                    if target_rel != unit.rel:
                        deps.add(target_rel)
    return graph


def _resolve_call(
    graph: CallGraph,
    unit: ModuleUnit,
    caller: FuncNode,
    call: ast.Call,
    local_types: Dict[str, str],
    local_classes: Set[str],
) -> List[str]:
    fn = call.func
    # helper(...) — same-module functions by simple name, else imported function
    if isinstance(fn, ast.Name):
        local = graph.local_candidates(unit.rel, fn.id)
        if local:
            # prefer module-level / same-class functions; fall back to all
            preferred = [
                fid
                for fid in local
                if graph.funcs[fid].cls is None or graph.funcs[fid].cls == caller.cls
            ]
            return preferred or local
        resolved = unit.imports.get(fn.id)
        if resolved and resolved.startswith(PACKAGE_PREFIX):
            return graph.function_by_dotted(resolved)
        return []
    if not isinstance(fn, ast.Attribute):
        return []
    recv = fn.value
    # self.method(...) / cls.method(...): enclosing class, bases included
    if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
        if caller.cls is not None:
            info = graph._class_by_rel_qual.get((unit.rel, caller.cls))
            if info is not None:
                hit = graph.resolve_method(info.dotted, fn.attr)
                if hit:
                    return hit
        return []
    # x.method(...) with a constructor-typed local
    if isinstance(recv, ast.Name):
        ctor = local_types.get(recv.id)
        if ctor:
            return graph.resolve_method(ctor, fn.attr)
        # ClassName.method(...) / imported-module function
        resolved = unit.resolve(recv)
        if resolved:
            if resolved.startswith(PACKAGE_PREFIX) or resolved == "metrics_tpu":
                hit = graph.resolve_method(resolved, fn.attr)
                if hit:
                    return hit
                return graph.function_by_dotted(f"{resolved}.{fn.attr}")
            return []
        if recv.id in local_classes:
            return graph.resolve_method(f"{unit.dotted}.{recv.id}", fn.attr)
        return []
    # self.attr.method(...) with a constructor-typed attribute
    if (
        isinstance(recv, ast.Attribute)
        and isinstance(recv.value, ast.Name)
        and recv.value.id in ("self", "cls")
        and caller.cls is not None
    ):
        info = graph._class_by_rel_qual.get((unit.rel, caller.cls))
        if info is not None:
            ctor = info.attr_types.get(recv.attr)
            if ctor:
                return graph.resolve_method(ctor, fn.attr)
        return []
    # module.sub.fn(...) through the import table
    resolved = unit.resolve(fn)
    if resolved and resolved.startswith(PACKAGE_PREFIX):
        hit = graph.function_by_dotted(resolved)
        if hit:
            return hit
        head, _, tail = resolved.rpartition(".")
        return graph.resolve_method(head, tail)
    return []


def get_call_graph(ctx: AnalysisContext) -> CallGraph:
    """The per-run cached graph — every pass shares one build."""
    graph = ctx.scratch.get(_SCRATCH_KEY)
    if graph is None:
        graph = build_call_graph(ctx.units)
        ctx.scratch[_SCRATCH_KEY] = graph
    return graph
