"""lock-witness / state-race: the runtime concurrency sanitizer passes.

Both are ``kind="dynamic"`` passes: instead of reading ASTs they install
the :mod:`tools.analyze.runtime.witness` instrumentation and drive the
serve fast burst plus a short soak drill in-process — producers submitting
from multiple threads, concurrent flush/checkpoint/chaos-sync/HTTP
traffic, then a kill→restore drill.  One drive is shared per engine run
(cached in the context scratch), so ``--pass lock-witness --pass
state-race`` pays the workload once.

* **lock-witness** replays the static lock-order rules against what
  actually happened: 2-cycles in the witnessed acquisition graph (rule
  ``runtime-lock-cycle``) and acquires that parked >
  :data:`~tools.analyze.runtime.witness.BLOCK_THRESHOLD_SECS` while the
  thread already held a lock (rule ``runtime-blocking-while-held``).  A
  green run over the burst is the dynamic counterexample machine for the
  baselined static findings: the ``EvalServer.checkpoint_now -> flush ->
  put_control`` chain runs here with every wait deadline-bounded.
* **state-race** runs the Eraser lockset algorithm over
  ``Metric._state`` writes: a state variable written from more than one
  thread whose writes share no common witnessed lock is flagged (rule
  ``unlocked-state-write``).

Both self-check their coverage (rule ``witness-no-coverage``): a driver
regression that silently stops creating locks or writing state turns the
pass red instead of vacuously green.  Findings flow through the standard
fingerprint/suppression/baseline path, so a witnessed-but-deliberate
pattern is baselined with a justification exactly like a static one.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from tools.analyze.engine import (
    AnalysisContext,
    AnalysisPass,
    Finding,
    register_pass,
)
from tools.analyze.runtime.witness import WitnessLog, witness_session

_SCRATCH = "runtime-witness-log"

# burst knobs: sized so the whole witnessed drive stays well under the
# tier-1 budget (<60s) while still forcing real cross-thread contention
BURST_RECORDS = 120
DRILL_RECORDS = 120
DRILL_CHECKPOINT_AT = 60
NUM_STREAMS = 8


def drive_serve_burst() -> None:
    """The workload both passes witness: serve fast burst + mini soak drill.

    Imports live inside the function on purpose — nothing in tools.analyze
    may import jax/metrics_tpu at module level (the ``--changed`` fast path
    depends on it).
    """
    import shutil
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    from metrics_tpu.checkpoint import CheckpointManager
    from metrics_tpu.multistream import MultiStreamMetric
    from metrics_tpu.regression import MeanSquaredError
    from metrics_tpu.serve import EvalServer, MetricRegistry, ServeConfig
    from metrics_tpu.serve.soak import exercise_chaos_sync, run_drill

    tmp = tempfile.mkdtemp(prefix="analyze-runtime-")
    try:
        registry = MetricRegistry()
        registry.register("mse", MeanSquaredError())
        registry.register(
            "tenants",
            MultiStreamMetric(MeanSquaredError(), num_streams=NUM_STREAMS),
            export_top_k=2,
        )
        config = ServeConfig(block_rows=16, flush_interval=0.05)
        manager = CheckpointManager(tmp + "/burst", rank=0, world_size=1)
        server = EvalServer(registry, config, manager).start()
        try:
            rng = np.random.default_rng(11)

            def produce(seed: int) -> None:
                prng = np.random.default_rng(seed)
                for _ in range(BURST_RECORDS):
                    p, t = prng.uniform(size=2).astype(np.float32)
                    server.submit("mse", (p, t), timeout=5.0)
                    server.submit(
                        "tenants",
                        (p, t),
                        stream_id=int(prng.integers(0, NUM_STREAMS)),
                        timeout=5.0,
                    )

            producers = [
                threading.Thread(target=produce, args=(31 + i,), name=f"producer-{i}")
                for i in range(2)
            ]
            for th in producers:
                th.start()
            # contention against the producers: the exact surfaces the static
            # pass baselined — flush-then-checkpoint under _ckpt_lock, the
            # registry-wide lock sweep, an operator sync, HTTP reads
            for path in ("/healthz", "/metrics", "/query?job=mse"):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}{path}", timeout=10.0
                ) as resp:
                    resp.read()
            server.flush(timeout=10.0)
            server.checkpoint_now()
            exercise_chaos_sync(registry, job="mse")
            for th in producers:
                th.join()
            server.flush(timeout=10.0)
            server.checkpoint_now()
        finally:
            if not server._stopped:
                server.stop()
        # short soak drill: kill -> restore -> drain, with the poller thread
        # issuing concurrent queries against the job locks
        run_drill(
            tmp + "/drill",
            n=DRILL_RECORDS,
            k=DRILL_CHECKPOINT_AT,
            lost_tail=5,
            block_rows=16,
            num_streams=NUM_STREAMS,
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def witnessed_run(
    workload: Optional[Callable[[], None]] = None,
    block_threshold: Optional[float] = None,
) -> WitnessLog:
    """Run ``workload`` (default: the serve burst) under the witness.

    The planted-fixture tests feed deliberate deadlocks/races through this
    same entry point (with a lowered ``block_threshold``), so "the
    sanitizer can actually see it" is a tested property, not an assumption.
    """
    kwargs = {} if block_threshold is None else {"block_threshold": block_threshold}
    with witness_session(**kwargs) as log:
        (workload or drive_serve_burst)()
    return log


def get_witness_log(ctx: AnalysisContext) -> WitnessLog:
    log = ctx.scratch.get(_SCRATCH)
    if log is None:
        log = witnessed_run()
        ctx.scratch[_SCRATCH] = log
    return log


@register_pass
class LockWitnessPass(AnalysisPass):
    name = "lock-witness"
    kind = "dynamic"
    description = (
        "drive the serve burst + soak drill under lock instrumentation: no "
        "cycles in the witnessed acquisition graph, nothing parked while "
        "holding a lock"
    )

    def check_package(self, ctx: AnalysisContext) -> List[Finding]:
        return self.findings_from_log(get_witness_log(ctx))

    def findings_from_log(self, log: WitnessLog) -> List[Finding]:
        out: List[Finding] = []
        for a, b, (rel, lineno, thread), (rel2, lineno2, thread2) in log.cycles():
            out.append(
                self.finding(
                    rel,
                    lineno,
                    "runtime-lock-cycle",
                    f"{a}<->{b}",
                    f"witnessed `{a}` held while acquiring `{b}` (thread "
                    f"{thread}) AND `{b}` held while acquiring `{a}` "
                    f"({rel2}:{lineno2}, thread {thread2}) — this interleaving "
                    "deadlocks when both paths run concurrently",
                )
            )
        for lock, held, secs, rel, lineno, thread in log.blocked:
            out.append(
                self.finding(
                    rel,
                    lineno,
                    "runtime-blocking-while-held",
                    f"{lock}:{'+'.join(held)}",
                    f"thread {thread} parked {secs:.2f}s acquiring `{lock}` "
                    f"while holding {list(held)} — every waiter on those locks "
                    "stalled with it; shrink the critical section or bound "
                    "the wait",
                )
            )
        if log.locks_created == 0 or not log.edges:
            out.append(
                self.finding(
                    "metrics_tpu/serve/server.py",
                    0,
                    "witness-no-coverage",
                    "locks",
                    f"the witnessed burst created {log.locks_created} "
                    f"package lock(s) and drew {len(log.edges)} acquisition "
                    "edge(s) — the instrumentation or the driver has rotted; "
                    "a green run with no coverage proves nothing",
                )
            )
        return out


@register_pass
class StateRacePass(AnalysisPass):
    name = "state-race"
    kind = "dynamic"
    description = (
        "Eraser-style lockset check over Metric._state writes during the "
        "witnessed serve burst: no state variable is mutated from multiple "
        "threads without a common lock"
    )

    def check_package(self, ctx: AnalysisContext) -> List[Finding]:
        return self.findings_from_log(get_witness_log(ctx))

    def findings_from_log(self, log: WitnessLog) -> List[Finding]:
        out: List[Finding] = []
        for otype, key, n_threads, n_writes, (rel, lineno) in log.races():
            out.append(
                self.finding(
                    rel,
                    lineno,
                    "unlocked-state-write",
                    f"{otype}.{key}",
                    f"state `{otype}.{key}` written {n_writes}x from "
                    f"{n_threads} threads with no common lock across the "
                    "writes — torn updates are a when, not an if; route the "
                    "writes through the owning job's lock",
                )
            )
        if not log.state_writes:
            out.append(
                self.finding(
                    "metrics_tpu/metric.py",
                    0,
                    "witness-no-coverage",
                    "state",
                    "the witnessed burst recorded zero Metric._state writes — "
                    "the _make_state_dict seam or the driver has rotted; a "
                    "green run with no coverage proves nothing",
                )
            )
        return out
