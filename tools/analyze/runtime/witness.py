"""Lock/state instrumentation for the runtime concurrency sanitizer.

``witness_session()`` monkeypatches, for the duration of a ``with`` block:

* ``threading.Lock`` / ``threading.RLock`` — the factories return recording
  proxies for locks **created from package code** (the creation stack walk
  finds a ``metrics_tpu/`` frame; everything else gets the raw primitive,
  so jax / pytest internals stay unobserved and near-zero overhead).
  ``queue.Queue`` built from a package frame is covered too: its internal
  mutex is created through the patched factory, and ``Condition.wait``
  releases/reacquires that proxy through the normal protocol, so held-sets
  stay correct across waits.
* ``metrics_tpu.metric._make_state_dict`` — every ``Metric._state`` dict
  (the ``__init__`` one and the per-call swap in ``_run_with_state``)
  becomes a subclass whose ``__setitem__`` logs (owner, key, thread,
  currently-held witnessed locks, site).

The log is pure data (:class:`WitnessLog`); turning it into findings is the
pass's job (``sanitizer.py``), so tests can drive planted scenarios through
the same substrate and assert on what it saw.

Lock identity is the creation site (``metrics_tpu/serve/registry.py:99``)
unless the owning code names the proxy better via the ``witness_name``
attribute — raw primitives reject attributes, which is why the hooks in
serve use ``try: lock.witness_name = ... except AttributeError: pass``:
free when instrumented, a no-op in production.  Module-level locks created
at import time predate the session and stay unobserved; the serve/sync
surface creates all its locks per-object, which is the surface this
sanitizer is for.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

PACKAGE_DIR = os.sep + "metrics_tpu" + os.sep

# an acquire that waited longer than this while the thread already held a
# witnessed lock is a blocking-while-held event.  The default sits above
# worst-case jit-compile stalls (a checkpoint quiesce legitimately waits
# ~2s for the consumer's first compile of a job update) but far below any
# real park — fixtures drop it via ``witness_session(block_threshold=...)``
BLOCK_THRESHOLD_SECS = 5.0


def _package_site(skip_self: bool = True) -> Optional[Tuple[str, int]]:
    """Nearest ``metrics_tpu/`` frame on the current stack: ``(rel, lineno)``."""
    frame = sys._getframe(1)
    while frame is not None:
        fname = frame.f_code.co_filename
        if PACKAGE_DIR in fname:
            rel = "metrics_tpu/" + fname.split(PACKAGE_DIR, 1)[1].replace(os.sep, "/")
            return rel, frame.f_lineno
        frame = frame.f_back
    return None


class _Held(threading.local):
    def __init__(self) -> None:
        self.stack: List["_WitnessedLock"] = []


class WitnessLog:
    """Everything one instrumented run observed.  Thread-safe via ``_mu``."""

    def __init__(self, block_threshold: float = BLOCK_THRESHOLD_SECS) -> None:
        self._mu = threading.Lock()  # created pre-patch: always a raw lock
        self._held = _Held()
        self.block_threshold = float(block_threshold)
        # lock graph: (held name, acquired name) -> (rel, lineno, thread name)
        # of the first acquisition site that drew the edge
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        # acquires that waited > threshold while holding another witnessed
        # lock: (lock, held tuple, seconds, rel, lineno, thread)
        self.blocked: List[Tuple[str, Tuple[str, ...], float, str, int, str]] = []
        # Eraser state machine per state variable (dict serial, owner type,
        # key): writes by the first thread before any other thread touches
        # the variable are the exclusive-init phase (no lockset constraint);
        # from the first second-thread write on, "lockset" is the running
        # intersection of held witnessed locks across the shared writes
        self.state_writes: Dict[Tuple[int, str, str], Dict[str, Any]] = {}
        self.locks_created = 0

    # ------------------------------------------------------------- lock side
    def held_names(self) -> Tuple[str, ...]:
        return tuple(lk.name for lk in self._held.stack)

    def on_acquired(self, lock: "_WitnessedLock", waited: float, site: Optional[Tuple[str, int]]) -> None:
        held = self._held.stack
        if held:
            rel, lineno = site if site else ("metrics_tpu", 0)
            tname = threading.current_thread().name
            with self._mu:
                for h in held:
                    if h.name != lock.name:
                        self.edges.setdefault((h.name, lock.name), (rel, lineno, tname))
                if waited > self.block_threshold:
                    self.blocked.append(
                        (lock.name, tuple(h.name for h in held), waited, rel, lineno, tname)
                    )
        held.append(lock)

    def on_released(self, lock: "_WitnessedLock") -> None:
        stack = self._held.stack
        # release order can differ from acquire order: drop the last entry
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    # ------------------------------------------------------------ state side
    def on_state_write(self, serial: int, owner_type: str, key: str) -> None:
        site = _package_site()
        locked = set(self.held_names())
        slot = (serial, owner_type, key)
        tid = threading.get_ident()
        with self._mu:
            rec = self.state_writes.get(slot)
            if rec is None:
                self.state_writes[slot] = {
                    "first_thread": tid,
                    "threads": {tid},
                    "lockset": None,  # exclusive-init phase: unconstrained
                    "writes": 1,
                    "site": site or ("metrics_tpu/metric.py", 0),
                }
                return
            rec["threads"].add(tid)
            rec["writes"] += 1
            if len(rec["threads"]) == 1:
                return  # still exclusive to the creating thread
            if rec["lockset"] is None:
                rec["lockset"] = locked  # first shared write seeds the set
            else:
                rec["lockset"] &= locked

    # ------------------------------------------------------------- analysis
    def cycles(self) -> List[Tuple[str, str, Tuple[str, int, str], Tuple[str, int, str]]]:
        """2-cycles in the witnessed acquisition graph (name granularity)."""
        out = []
        for (a, b), info in sorted(self.edges.items()):
            if a < b and (b, a) in self.edges:
                out.append((a, b, info, self.edges[(b, a)]))
        return out

    def races(self) -> List[Tuple[str, str, int, int, Tuple[str, int]]]:
        """State variables whose SHARED writes (post-init, >1 thread) hold no
        common lock: ``(owner type, key, n threads, n writes, site)``."""
        out = []
        seen: Set[Tuple[str, str]] = set()
        for (_serial, otype, key), rec in sorted(
            self.state_writes.items(), key=lambda kv: (kv[0][1], kv[0][2], kv[0][0])
        ):
            if (
                len(rec["threads"]) > 1
                and rec["lockset"] is not None
                and not rec["lockset"]
                and (otype, key) not in seen  # one finding per variable, not per instance
            ):
                seen.add((otype, key))
                out.append((otype, key, len(rec["threads"]), rec["writes"], rec["site"]))
        return out


class _WitnessedLock:
    """Recording proxy around a ``_thread.lock`` / ``RLock``.

    Supports attribute assignment (``witness_name``) precisely because the
    raw primitives do not — that asymmetry is the no-op-in-production hook.
    """

    def __init__(self, inner: Any, log: WitnessLog, site: Optional[Tuple[str, int]]) -> None:
        self._inner = inner
        self._log = log
        self.created_at = site or ("metrics_tpu", 0)
        self._name: Optional[str] = None

    @property
    def name(self) -> str:
        return self._name or f"{self.created_at[0]}:{self.created_at[1]}"

    @property
    def witness_name(self) -> Optional[str]:
        return self._name

    @witness_name.setter
    def witness_name(self, value: str) -> None:
        self._name = str(value)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.monotonic()
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._log.on_acquired(self, time.monotonic() - t0, _package_site())
        return got

    # Condition() duck-types on _release_save/_acquire_restore/_is_owned by
    # ATTRIBUTE ACCESS (plain Lock lacks them, RLock has them), so they must
    # raise AttributeError here exactly when the inner lock lacks them —
    # defining them as normal methods would break Condition(plain Lock).
    def __getattr__(self, name: str) -> Any:
        inner = self.__dict__.get("_inner")
        if inner is None or name.startswith("__"):
            raise AttributeError(name)
        inner_attr = getattr(inner, name)  # AttributeError propagates
        if name == "_release_save":

            def release_save() -> Any:
                state = inner_attr()
                self._log.on_released(self)
                return state

            return release_save
        if name == "_acquire_restore":

            def acquire_restore(state: Any) -> None:
                inner_attr(state)
                self._log.on_acquired(self, 0.0, None)

            return acquire_restore
        return inner_attr

    def release(self) -> None:
        self._inner.release()
        self._log.on_released(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<witnessed {self.name} {self._inner!r}>"


_PYTREE_REGISTERED = False


def _ensure_pytree_registration() -> None:
    """jax treats exact ``dict`` as a pytree but subclasses as LEAVES; state
    dicts flow wholesale into jitted calls (``self._jitted_forward(
    self._state, ...)``), so the recording subclass must flatten like a
    dict.  Registration is process-global and idempotent by design — it
    only concerns our own class."""
    global _PYTREE_REGISTERED
    if _PYTREE_REGISTERED:
        return
    import jax

    jax.tree_util.register_pytree_node(
        _RecordingStateDict,
        lambda d: ([d[k] for k in sorted(d)], tuple(sorted(d))),
        lambda keys, vals: dict(zip(keys, vals)),
    )
    _PYTREE_REGISTERED = True


_DICT_SERIALS = iter(range(1, 1 << 62))


class _RecordingStateDict(dict):
    """``Metric._state`` replacement: logs every key write with context.

    Each instance carries a process-unique serial — keying the log on
    ``id(owner)`` would alias reused ids across garbage-collected metrics,
    and keying on the owner alone would merge the per-call scratch dicts
    ``_run_with_state`` swaps in (which are call-local by construction)
    with the genuinely shared persistent state dict."""

    __slots__ = ("_serial", "_owner_type", "_log")

    def __init__(self, owner: Any, log: WitnessLog) -> None:
        super().__init__()
        self._serial = next(_DICT_SERIALS)
        self._owner_type = type(owner).__name__
        self._log = log

    def __setitem__(self, key: str, value: Any) -> None:
        self._log.on_state_write(self._serial, self._owner_type, key)
        super().__setitem__(key, value)

    def setdefault(self, key: str, default: Any = None) -> Any:
        if key not in self:
            self._log.on_state_write(self._serial, self._owner_type, key)
        return super().setdefault(key, default)

    def update(self, *args: Any, **kwargs: Any) -> None:
        staged = dict(*args, **kwargs)
        for key in staged:
            self._log.on_state_write(self._serial, self._owner_type, key)
        super().update(staged)


class witness_session:
    """``with witness_session() as log:`` — patch, run, restore.

    Patching is process-global, so sessions must not nest or overlap
    (asserted).  Everything touched is restored on exit even when the
    driven workload raises.
    """

    _active: Optional["witness_session"] = None

    def __init__(self, block_threshold: float = BLOCK_THRESHOLD_SECS) -> None:
        self.log = WitnessLog(block_threshold=block_threshold)

    def __enter__(self) -> WitnessLog:
        assert witness_session._active is None, "witness sessions cannot nest"
        witness_session._active = self
        log = self.log
        self._saved_lock = threading.Lock
        self._saved_rlock = threading.RLock

        def make(factory):
            def wrapped() -> Any:
                inner = factory()
                site = _package_site()
                if site is None:
                    return inner  # out-of-package lock: stay invisible
                log.locks_created += 1
                return _WitnessedLock(inner, log, site)

            return wrapped

        threading.Lock = make(self._saved_lock)  # type: ignore[misc]
        threading.RLock = make(self._saved_rlock)  # type: ignore[misc]

        import metrics_tpu.metric as metric_mod

        _ensure_pytree_registration()
        self._metric_mod = metric_mod
        self._saved_factory = metric_mod._make_state_dict
        metric_mod._make_state_dict = lambda owner: _RecordingStateDict(owner, log)
        return log

    def __exit__(self, *exc: Any) -> None:
        threading.Lock = self._saved_lock  # type: ignore[misc]
        threading.RLock = self._saved_rlock  # type: ignore[misc]
        self._metric_mod._make_state_dict = self._saved_factory
        witness_session._active = None
