"""Runtime concurrency sanitizer: the dynamic half of the analyze engine.

The static passes (lock-order, serve-blocking, trace-safety) reason about
what the code *could* do; the passes here watch what it *actually does*.
``witness.py`` is the instrumentation substrate — factory-level wrapping of
``threading.Lock``/``RLock`` plus a write-recording ``Metric._state`` dict —
and ``sanitizer.py`` registers the two dynamic passes (``lock-witness``,
``state-race``) that drive the serve fast burst + a short soak drill under
that instrumentation and report through the same fingerprint/baseline
machinery as every static pass:

    python -m tools.analyze --pass lock-witness --pass state-race

Import-time cost here must stay stdlib-only: the engine imports this
package to register the passes, and the ``--changed`` fast path relies on
nothing in ``tools.analyze`` importing jax or metrics_tpu at module level.
The serve driver lives behind a function boundary for exactly that reason.
"""

from tools.analyze.runtime import sanitizer  # noqa: F401  (register passes)
from tools.analyze.runtime.witness import WitnessLog, witness_session  # noqa: F401
