"""Unified static-analysis engine for the metrics_tpu repo.

Run it as ``python -m tools.analyze`` (see ``--help``); use
:func:`run_passes` in-process (bench does).  The engine, suppression
model, and pass API live in :mod:`tools.analyze.engine`; the bundled
passes in :mod:`tools.analyze.passes`.
"""

from tools.analyze.engine import (  # noqa: F401
    BASELINE_PATH,
    PASSES,
    AnalysisContext,
    AnalysisPass,
    Finding,
    ModuleUnit,
    Report,
    analyze_source,
    analyze_sources,
    discover_units,
    load_baseline,
    register_pass,
    run_passes,
    update_baseline,
)

__all__ = [
    "BASELINE_PATH",
    "PASSES",
    "AnalysisContext",
    "AnalysisPass",
    "Finding",
    "ModuleUnit",
    "Report",
    "analyze_source",
    "analyze_sources",
    "discover_units",
    "load_baseline",
    "register_pass",
    "run_passes",
    "update_baseline",
]

# importing the subpackage registers the bundled passes into PASSES
from tools.analyze import passes as _passes  # noqa: E402,F401
