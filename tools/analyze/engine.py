"""Core of the unified static-analysis engine (``tools/analyze``).

One AST/scope engine shared by every pass: package-walk module discovery,
per-module import-alias resolution (so ``import jax.numpy as np`` and
``import numpy as jnp`` are told apart), inline suppression markers, a pass
registry with per-pass severity, and a checked-in suppression baseline
(``tools/analyze/baseline.json``) keyed on stable finding fingerprints
rather than line numbers.

Suppression has three layers, from broadest to narrowest:

* **file opt-out** — a ``# analyze: skip-file[pass-a,pass-b] -- reason``
  comment anywhere in a module removes it from those passes' scope (``*``
  opts out of everything).  This replaces the old hand-maintained
  ``LINTED_MODULES`` / ``LINTED_DIRS`` lists: every module found by the
  package walk is analyzed by default, and the deliberate exceptions carry
  their justification in the file itself.
* **line ignore** — a trailing ``# analyze: ignore[pass-a] -- reason``
  suppresses findings any listed pass reports on that line.
* **baseline** — ``baseline.json`` maps finding fingerprints (pass, module,
  rule, detail) to an occurrence count and a one-line justification; it is
  how deliberate repo-wide patterns are accepted without editing the code.
  ``python -m tools.analyze --update-baseline`` rewrites it from the
  current findings, preserving existing justifications.

Passes subclass :class:`AnalysisPass` and register with
:func:`register_pass`; AST passes implement ``check_module`` (plus
``finish`` for cross-module aggregation, e.g. the lock-acquisition graph),
dynamic passes implement ``check_package``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE = "metrics_tpu"
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")

_MARKER_RE = re.compile(
    r"#\s*analyze:\s*(?P<kind>skip-file|ignore)\[(?P<names>[^\]]+)\]"
    r"(?:\s*--\s*(?P<reason>.*))?"
)


# ---------------------------------------------------------------------------
# findings


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analysis finding with a line-drift-stable fingerprint.

    ``rule`` is the short machine id of the check that fired; ``detail`` is
    the stable context string (usually ``<function qualname>:<offender>``)
    that, together with pass and module, keys the baseline — line numbers
    are display-only so baselines survive unrelated edits.
    """

    pass_name: str
    module: str
    lineno: int
    rule: str
    detail: str
    message: str
    severity: str = "error"

    @property
    def key(self) -> str:
        return "::".join((self.pass_name, self.module, self.rule, self.detail))

    def render(self) -> str:
        loc = f"{self.module}:{self.lineno}" if self.lineno else self.module
        return f"{self.pass_name}: {loc}: [{self.rule}] {self.message}"

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# module units


class ModuleUnit:
    """One source file: lazily-parsed AST, markers, and import aliases."""

    def __init__(self, rel: str, source: str, path: Optional[str] = None) -> None:
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.path = path
        self.parse_error: Optional[SyntaxError] = None
        self._tree: Optional[ast.Module] = None
        self._parsed = False
        self._imports: Optional[Dict[str, str]] = None
        # markers
        self.skip_passes: Set[str] = set()
        self.skip_reasons: Dict[str, str] = {}
        self.ignores: Dict[int, Set[str]] = {}
        self._scan_markers()

    # ------------------------------------------------------------- markers
    def _scan_markers(self) -> None:
        for lineno, line in enumerate(self.source.splitlines(), start=1):
            m = _MARKER_RE.search(line)
            if not m:
                continue
            names = {n.strip() for n in m.group("names").split(",") if n.strip()}
            if m.group("kind") == "skip-file":
                self.skip_passes |= names
                reason = (m.group("reason") or "").strip()
                for n in names:
                    self.skip_reasons.setdefault(n, reason)
            else:
                self.ignores.setdefault(lineno, set()).update(names)

    def skips(self, pass_name: str) -> bool:
        return pass_name in self.skip_passes or "*" in self.skip_passes

    def ignored(self, pass_name: str, lineno: int) -> bool:
        names = self.ignores.get(lineno)
        return bool(names) and (pass_name in names or "*" in names)

    # --------------------------------------------------------------- parse
    @property
    def tree(self) -> Optional[ast.Module]:
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.source, filename=self.rel)
            except SyntaxError as err:
                self.parse_error = err
        return self._tree

    @property
    def dotted(self) -> str:
        """This module's dotted import path (``metrics_tpu.serve.ingest``)."""
        mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        parts = mod.split("/")
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    # -------------------------------------------------------------- imports
    @property
    def imports(self) -> Dict[str, str]:
        """Local name -> dotted module/attr it aliases.

        ``import jax.numpy as np`` maps ``np -> jax.numpy`` while
        ``import numpy as np`` maps ``np -> numpy`` — this is what lets the
        trace-safety pass tell a host ``asarray`` from a device one.
        """
        if self._imports is None:
            self._imports = {}
            tree = self.tree
            if tree is not None:
                pkg_parts = self.dotted.split(".")
                if self.rel.endswith("/__init__.py"):
                    pkg_parts = pkg_parts + [""]  # relative level 1 = this pkg
                for node in ast.walk(tree):
                    if isinstance(node, ast.Import):
                        for alias in node.names:
                            if alias.asname:
                                self._imports[alias.asname] = alias.name
                            else:
                                head = alias.name.split(".")[0]
                                self._imports[head] = head
                    elif isinstance(node, ast.ImportFrom):
                        if node.level:
                            base_parts = pkg_parts[: len(pkg_parts) - node.level]
                            mod = ".".join(base_parts + ([node.module] if node.module else []))
                        else:
                            mod = node.module or ""
                        for alias in node.names:
                            local = alias.asname or alias.name
                            self._imports[local] = f"{mod}.{alias.name}" if mod else alias.name
        return self._imports

    def resolve(self, expr: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute chain through the import aliases.

        ``np.asarray`` with ``import numpy as np`` -> ``numpy.asarray``.
        Returns ``None`` for expressions that are not plain dotted names.
        """
        dotted = dotted_name(expr)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        base = self.imports.get(head, head)
        return f"{base}.{rest}" if rest else base


def dotted_name(expr: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else ``None``."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def expr_text(expr: ast.AST) -> str:
    """Compact source-ish text for an expression (lock identities etc.)."""
    try:
        return ast.unparse(expr)
    except Exception:
        return dotted_name(expr) or "<expr>"


def walk_with_scope(tree: ast.Module) -> Iterator[Tuple[ast.AST, str]]:
    """Yield ``(node, enclosing_qualname)`` pairs; module level is ``""``."""

    def visit(node: ast.AST, scope: str) -> Iterator[Tuple[ast.AST, str]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = f"{scope}.{child.name}" if scope else child.name
                yield child, scope
                yield from visit(child, inner)
            elif isinstance(child, ast.ClassDef):
                inner = f"{scope}.{child.name}" if scope else child.name
                yield child, scope
                yield from visit(child, inner)
            elif isinstance(child, ast.Lambda):
                inner = f"{scope}.<lambda@{child.lineno}>" if scope else f"<lambda@{child.lineno}>"
                yield child, scope
                yield from visit(child, inner)
            else:
                yield child, scope
                yield from visit(child, scope)

    yield from visit(tree, "")


# ---------------------------------------------------------------------------
# pass registry


class AnalysisPass:
    """Base class for one analysis pass.

    ``kind`` is ``"ast"`` (per-module ``check_module`` + optional cross-
    module ``finish``) or ``"dynamic"`` (``check_package`` imports the live
    package).  ``severity`` is the default stamped on findings.
    """

    name: str = ""
    description: str = ""
    severity: str = "error"
    kind: str = "ast"

    def applies(self, unit: ModuleUnit) -> bool:
        return True

    def check_module(self, unit: ModuleUnit, ctx: "AnalysisContext") -> List[Finding]:
        return []

    def finish(self, ctx: "AnalysisContext") -> List[Finding]:
        return []

    def check_package(self, ctx: "AnalysisContext") -> List[Finding]:
        return []

    # helper so passes build findings with their own name/severity
    def finding(
        self,
        module: str,
        lineno: int,
        rule: str,
        detail: str,
        message: str,
        severity: Optional[str] = None,
    ) -> Finding:
        return Finding(
            pass_name=self.name,
            module=module,
            lineno=lineno,
            rule=rule,
            detail=detail,
            message=message,
            severity=severity or self.severity,
        )


PASSES: Dict[str, AnalysisPass] = {}


def register_pass(cls: Callable[[], AnalysisPass]):
    """Class decorator: instantiate and add to the global registry."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"{cls!r} has no pass name")
    if inst.name in PASSES:
        raise ValueError(f"duplicate pass name {inst.name!r}")
    PASSES[inst.name] = inst
    return cls


# ---------------------------------------------------------------------------
# discovery


def discover_units(root: Optional[str] = None, package: str = PACKAGE) -> List[ModuleUnit]:
    """Package walk: every ``*.py`` under ``<root>/<package>``, sorted.

    This is the single source of scope truth — a new module is analyzed by
    default; opting out takes an explicit ``skip-file`` marker with a
    reason, not absence from a hand-maintained list.
    """
    root = os.path.abspath(root or REPO_ROOT)
    pkg_dir = os.path.join(root, package)
    units: List[ModuleUnit] = []
    for base, dirs, files in sorted(os.walk(pkg_dir)):
        dirs.sort()
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(base, fname)
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            rel = os.path.relpath(path, root)
            units.append(ModuleUnit(rel, source, path=path))
    return units


class AnalysisContext:
    """Everything a pass can see: the module table plus per-run scratch."""

    def __init__(self, units: List[ModuleUnit], root: str) -> None:
        self.units = units
        self.root = root
        self.scratch: Dict[str, Any] = {}

    def unit(self, rel: str) -> Optional[ModuleUnit]:
        rel = rel.replace(os.sep, "/")
        for u in self.units:
            if u.rel == rel:
                return u
        return None


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path: str = BASELINE_PATH) -> Dict[str, Dict[str, Any]]:
    """``{finding key: {"count": int, "justification": str}}`` (empty if absent)."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return dict(data.get("entries", {}))


def split_baselined(
    findings: Sequence[Finding], baseline: Dict[str, Dict[str, Any]]
) -> Tuple[List[Finding], List[Finding]]:
    """Partition into (fresh, baselined); each baseline entry absorbs up to
    ``count`` occurrences of its fingerprint."""
    budget = {key: int(entry.get("count", 0)) for key, entry in baseline.items()}
    fresh: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            suppressed.append(f)
        else:
            fresh.append(f)
    return fresh, suppressed


def update_baseline(
    findings: Sequence[Finding],
    path: str = BASELINE_PATH,
    default_justification: str = "TODO: justify",
) -> Dict[str, Dict[str, Any]]:
    """Rewrite the baseline from the current findings.

    Existing justifications are preserved key-by-key; new fingerprints get
    ``default_justification`` so a review can't miss them.  Entries whose
    finding disappeared are dropped — the baseline only ever shrinks or
    gains reviewed entries.
    """
    previous = load_baseline(path)
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    entries = {
        key: {
            "count": n,
            "justification": previous.get(key, {}).get(
                "justification", default_justification
            ),
        }
        for key, n in sorted(counts.items())
    }
    payload = {
        "comment": (
            "Suppression baseline for python -m tools.analyze. Keys are "
            "pass::module::rule::detail fingerprints (line-number free). "
            "Regenerate with --update-baseline; every entry needs a one-line "
            "justification."
        ),
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return entries


# ---------------------------------------------------------------------------
# runner


@dataclasses.dataclass
class Report:
    """Outcome of one engine run: fresh findings gate, the rest is telemetry."""

    findings: List[Finding]
    baselined: List[Finding]
    per_pass: Dict[str, Dict[str, int]]
    modules_analyzed: int

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "findings_total": len(self.findings),
            "baselined_total": len(self.baselined),
            "modules_analyzed": self.modules_analyzed,
            "per_pass": self.per_pass,
            "findings": [f.to_json() for f in self.findings],
        }


def run_passes(
    pass_names: Optional[Sequence[str]] = None,
    root: Optional[str] = None,
    units: Optional[List[ModuleUnit]] = None,
    baseline_path: Optional[str] = BASELINE_PATH,
    collect_all: bool = False,
    only: Optional[Set[str]] = None,
) -> Report:
    """Run the selected passes (default: all) over the package walk.

    ``collect_all=True`` skips baseline filtering (used by
    ``--update-baseline``, which needs the raw findings).

    ``only`` restricts per-module analysis (``check_module``) to the given
    rel paths while every unit stays visible to the cross-module ``finish``
    halves — the call graph and lazy scans still see the whole package, so
    transitive findings rooted in a restricted module keep their full
    provenance.  This is the ``--changed`` substrate; the full run remains
    the authority.
    """
    # ensure the bundled passes are registered even when the caller imported
    # engine directly
    from tools.analyze import passes as _passes  # noqa: F401

    root = os.path.abspath(root or REPO_ROOT)
    if units is None:
        units = discover_units(root)
    ctx = AnalysisContext(units, root)
    if only is not None:
        # incremental runs must not import the live package (jax): passes
        # with a live-probe half skip it here, like they do in fixture mode
        ctx.scratch["incremental_mode"] = True
    selected = list(pass_names) if pass_names else sorted(PASSES)
    unknown = [n for n in selected if n not in PASSES]
    if unknown:
        raise KeyError(f"unknown pass(es) {unknown}; registered: {sorted(PASSES)}")

    raw: List[Finding] = []
    for name in selected:
        p = PASSES[name]
        if p.kind == "dynamic":
            raw.extend(p.check_package(ctx))
            continue
        for unit in units:
            if only is not None and unit.rel not in only:
                continue
            if unit.skips(p.name) or not p.applies(unit):
                continue
            if unit.tree is None:
                raw.append(
                    p.finding(
                        unit.rel,
                        unit.parse_error.lineno or 0 if unit.parse_error else 0,
                        "syntax-error",
                        "parse",
                        f"does not parse: {unit.parse_error and unit.parse_error.msg}",
                    )
                )
                continue
            for f in p.check_module(unit, ctx):
                if not unit.ignored(p.name, f.lineno):
                    raw.append(f)
        for f in p.finish(ctx):
            unit = ctx.unit(f.module)
            if unit is not None and (unit.skips(p.name) or unit.ignored(p.name, f.lineno)):
                continue
            raw.append(f)

    baseline = {} if (collect_all or not baseline_path) else load_baseline(baseline_path)
    fresh, suppressed = split_baselined(raw, baseline)
    per_pass: Dict[str, Dict[str, int]] = {
        name: {"findings": 0, "baselined": 0} for name in selected
    }
    for f in fresh:
        per_pass[f.pass_name]["findings"] += 1
    for f in suppressed:
        per_pass[f.pass_name]["baselined"] += 1
    return Report(
        findings=fresh,
        baselined=suppressed,
        per_pass=per_pass,
        modules_analyzed=len(units),
    )


def analyze_source(
    pass_name: str,
    source: str,
    rel: str = "metrics_tpu/synthetic.py",
) -> List[Finding]:
    """Run ONE AST pass over one source string under a pretend path.

    The fixture/test entry point (and what the legacy ``lint_source`` shims
    call): markers in the source are honored, the baseline is not.
    """
    from tools.analyze import passes as _passes  # noqa: F401

    p = PASSES[pass_name]
    if p.kind != "ast":
        raise ValueError(f"pass {pass_name!r} is dynamic; analyze_source needs an AST pass")
    unit = ModuleUnit(rel, source)
    ctx = AnalysisContext([unit], REPO_ROOT)
    ctx.scratch["fixture_mode"] = True  # passes skip live-package halves
    if unit.skips(p.name) or not p.applies(unit):
        return []
    if unit.tree is None:
        err = unit.parse_error
        return [
            p.finding(
                unit.rel,
                (err.lineno or 0) if err else 0,
                "syntax-error",
                "parse",
                f"does not parse: {err and err.msg}",
            )
        ]
    out = [f for f in p.check_module(unit, ctx) if not unit.ignored(p.name, f.lineno)]
    for f in p.finish(ctx):
        if not unit.ignored(p.name, f.lineno):
            out.append(f)
    return out


def analyze_sources(
    pass_name: str,
    sources: Dict[str, str],
) -> List[Finding]:
    """Run ONE AST pass over a dict of ``{rel path: source}`` pretend modules.

    The multi-module sibling of :func:`analyze_source`, for fixtures that
    exercise cross-module behavior (transitive lock chains, traced regions
    leaking across imports): all units share one context, so the call graph
    links them exactly as a real package walk would.
    """
    from tools.analyze import passes as _passes  # noqa: F401

    p = PASSES[pass_name]
    if p.kind != "ast":
        raise ValueError(f"pass {pass_name!r} is dynamic; analyze_sources needs an AST pass")
    units = [ModuleUnit(rel, source) for rel, source in sorted(sources.items())]
    ctx = AnalysisContext(units, REPO_ROOT)
    ctx.scratch["fixture_mode"] = True  # passes skip live-package halves
    out: List[Finding] = []
    for unit in units:
        if unit.skips(p.name) or not p.applies(unit):
            continue
        if unit.tree is None:
            err = unit.parse_error
            out.append(
                p.finding(
                    unit.rel,
                    (err.lineno or 0) if err else 0,
                    "syntax-error",
                    "parse",
                    f"does not parse: {err and err.msg}",
                )
            )
            continue
        out.extend(
            f for f in p.check_module(unit, ctx) if not unit.ignored(p.name, f.lineno)
        )
    for f in p.finish(ctx):
        unit = ctx.unit(f.module)
        if unit is not None and (unit.skips(p.name) or unit.ignored(p.name, f.lineno)):
            continue
        out.append(f)
    return out
