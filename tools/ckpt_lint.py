"""Checkpoint-serializer coverage lint — thin shim over ``tools.analyze``.

The checks live in the ``ckpt-serializers`` pass
(``tools/analyze/passes/ckpt_serializers.py``); this module keeps the
legacy entry point and API (``lint`` / ``lint_roundtrip``) alive.  Prefer
``python -m tools.analyze``.
"""

from __future__ import annotations

import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # imported by bare name with tools/ on sys.path
    sys.path.insert(0, _REPO)

from tools.analyze.passes.ckpt_serializers import (
    coverage_problems,
    roundtrip_problems,
)


def lint() -> List[str]:
    return [message for _rule, _detail, message in coverage_problems()]


def lint_roundtrip() -> List[str]:
    return [message for _rule, _detail, message in roundtrip_problems()]


def main() -> int:
    problems = lint() + lint_roundtrip()
    for p in problems:
        print(p)
    if problems:
        print(f"ckpt_lint: {len(problems)} problem(s)")
        return 1
    print("ckpt_lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
