"""Serve request-path blocking lint — thin shim over ``tools.analyze``.

The checks live in the ``serve-blocking`` pass
(``tools/analyze/passes/serve_blocking.py``); this module keeps the legacy
entry point and API alive.  Scope is now the package walk (every
``metrics_tpu/serve/`` module, opt-out via ``skip-file`` markers) instead of
the old hand-maintained ``LINTED_MODULES`` tuple.  Prefer
``python -m tools.analyze``.
"""

from __future__ import annotations

import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # imported by bare name with tools/ on sys.path
    sys.path.insert(0, _REPO)

from tools.analyze import analyze_source, discover_units, run_passes
from tools.analyze.passes.serve_blocking import (  # noqa: F401  (legacy API)
    BANNED_IMPORT_PREFIXES,
    BLOCKING_CALLS,
    SCOPE_PREFIX,
)


def _linted_modules() -> tuple:
    """Discovery-backed replacement for the old hand-listed tuple."""
    return tuple(
        u.rel[len("metrics_tpu/"):]
        for u in discover_units()
        if u.rel.startswith(SCOPE_PREFIX) and not u.skips("serve-blocking")
    )


LINTED_MODULES = _linted_modules()


def lint_source(src: str, filename: str) -> List[str]:
    """Lint one source string unconditionally (legacy behavior)."""
    rel = filename.replace(os.sep, "/")
    if not rel.startswith(SCOPE_PREFIX):
        rel = SCOPE_PREFIX + os.path.basename(rel)
    return [f.render() for f in analyze_source("serve-blocking", src, rel=rel)]


def lint() -> List[str]:
    report = run_passes(["serve-blocking"], baseline_path=None)
    return [f.render() for f in report.findings]


def main() -> int:
    problems = lint()
    for p in problems:
        print(p)
    if problems:
        print(f"serve_lint: {len(problems)} problem(s)")
        return 1
    print("serve_lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
