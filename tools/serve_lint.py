"""Static check: serve request paths never block on a collective or KV wait.

The serve layer's responsiveness claim is structural: an HTTP handler or
the queue-consumer loop must never sit in a mesh collective, a distributed
barrier, or a parked key-value wait, because a peer that died (or a
scheduler that paused it) would turn one slow tenant read into a hung
service.  ``MetricRegistry.register`` enforces the dynamic half (it forces
``sync_on_compute`` / ``dist_sync_on_step`` off); this linter enforces the
static half: the request-path modules simply do not *spell* any blocking
primitive.

AST-walked modules — everything that runs on an HTTP thread or the
consumer thread:

* ``metrics_tpu/serve/httpd.py``
* ``metrics_tpu/serve/ingest.py``
* ``metrics_tpu/serve/registry.py``
* ``metrics_tpu/serve/traffic.py``

``server.py`` and ``soak.py`` are deliberately NOT linted: the durability
loop checkpoints (which barriers across ranks by design) and the soak
harness fires explicit operator syncs — both off the request path.

Run directly (``python tools/serve_lint.py``) or via
``tests/serve/test_serve_lint.py``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# request-path modules, relative to the repo root
LINTED_MODULES = (
    os.path.join("metrics_tpu", "serve", "httpd.py"),
    os.path.join("metrics_tpu", "serve", "ingest.py"),
    os.path.join("metrics_tpu", "serve", "registry.py"),
    os.path.join("metrics_tpu", "serve", "traffic.py"),
)

# call names that block on peers: collectives, barriers, KV-store waits,
# checkpoint commits (which barrier internally), and explicit metric syncs
BLOCKING_CALLS = {
    "sync",
    "unsync",
    "sync_context",
    "wait_at_barrier",
    "blocking_key_value_get",
    "blocking_key_value_get_bytes",
    "all_gather",
    "all_gather_bytes",
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "preflight_check",
    "save",
    "save_now",
    "maybe_save",
    "restore",
    "barrier",
}

# importing the distributed/checkpoint machinery into a request-path module
# is the gateway violation — flag it at the import, where intent is clearest
BANNED_IMPORT_PREFIXES = (
    "metrics_tpu.parallel",
    "metrics_tpu.checkpoint",
    "jax.experimental.multihost_utils",
)


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def lint_source(src: str, filename: str) -> List[str]:
    """Lint one request-path module's source; returns violation strings."""
    problems: List[str] = []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as err:
        return [f"{filename}:{err.lineno}: does not parse: {err.msg}"]
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in BLOCKING_CALLS:
                problems.append(
                    f"{filename}:{node.lineno}: `{name}(...)` can block on a "
                    "peer; request paths must read local state only (move it "
                    "to server.py's durability loop or an operator action)"
                )
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            else:
                mod = node.module or ""
                names = [mod] + [f"{mod}.{a.name}" for a in node.names]
            for name in names:
                if any(
                    name == p or name.startswith(p + ".")
                    for p in BANNED_IMPORT_PREFIXES
                ):
                    problems.append(
                        f"{filename}:{node.lineno}: imports `{name}`; the "
                        "distributed/checkpoint machinery must stay out of "
                        "request-path modules"
                    )
    return problems


def lint() -> List[str]:
    """Lint every request-path serve module."""
    problems: List[str] = []
    for rel in LINTED_MODULES:
        path = os.path.join(_REPO_ROOT, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: linted module is missing")
            continue
        with open(path, "r", encoding="utf-8") as fh:
            problems.extend(lint_source(fh.read(), rel))
    return problems


def main() -> int:
    problems = lint()
    for line in problems:
        print(f"serve_lint: {line}", file=sys.stderr)
    if problems:
        print(f"serve_lint: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print("serve_lint: serve request paths are collective-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
