"""Convert published torch checkpoints into metrics_tpu Flax param pytrees.

The reference buys its feature extractors from torch packages
(``torch-fidelity`` Inception, ``lpips`` VGG/AlexNet — reference
``image/fid.py:41-58``, ``image/lpip.py:34``); this tool maps those
checkpoints onto the first-party Flax backbones so scores match published
numbers.  Conventions handled:

* conv kernels: torch OIHW -> flax HWIO
* 1x1 LPIPS heads: torch (1, C, 1, 1) -> flax (1, 1, C, 1)
* linear: torch (out, in) -> flax (in, out)
* BatchNorm running stats -> the ``batch_stats`` collection

BERTScore needs no converter: HuggingFace's
``FlaxAutoModel.from_pretrained(..., from_pt=True)`` performs the torch->flax
conversion natively.

Usage (on a machine with the torch checkpoints available)::

    import torch
    from tools.convert_weights import convert_lpips_vgg16
    params = convert_lpips_vgg16(torch.load("lpips_vgg.pth"))
    np.savez("lpips_vgg_flax.npz", **flatten_params(params))
"""

from typing import Any, Dict, List, Mapping, Tuple

import numpy as np

# torchvision layer indices of the conv layers inside `features`
VGG16_CONV_INDICES: Tuple[int, ...] = (0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28)
ALEXNET_CONV_INDICES: Tuple[int, ...] = (0, 3, 6, 8, 10)

# our _LpipsBackbone('vgg') names, stage-major (matches VGG16_CONV_INDICES order)
_VGG_FLAX_NAMES: Tuple[str, ...] = (
    "stage0_conv0", "stage0_conv1",
    "stage1_conv0", "stage1_conv1",
    "stage2_conv0", "stage2_conv1", "stage2_conv2",
    "stage3_conv0", "stage3_conv1", "stage3_conv2",
    "stage4_conv0", "stage4_conv1", "stage4_conv2",
)
_ALEX_FLAX_NAMES: Tuple[str, ...] = ("conv0", "conv1", "conv2", "conv3", "conv4")

# torchvision squeezenet1_1 `features` indices of the Fire modules
SQUEEZENET_FIRE_INDICES: Tuple[int, ...] = (3, 4, 6, 7, 9, 10, 11, 12)


def conv_to_flax(weight: np.ndarray) -> np.ndarray:
    """torch conv kernel OIHW -> flax HWIO."""
    return np.transpose(np.asarray(weight), (2, 3, 1, 0))


def linear_to_flax(weight: np.ndarray) -> np.ndarray:
    """torch linear (out, in) -> flax (in, out)."""
    return np.transpose(np.asarray(weight), (1, 0))


def _to_numpy(x: Any) -> np.ndarray:
    # works for torch tensors (via .detach().cpu().numpy()) and arrays
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _conv_entry(state_dict: Mapping[str, Any], key: str) -> Dict[str, Any]:
    w = state_dict.get(f"{key}.weight")
    b = state_dict.get(f"{key}.bias")
    if w is None or b is None:
        raise KeyError(f"missing conv weights for {key}")
    return {"kernel": conv_to_flax(_to_numpy(w)), "bias": _to_numpy(b)}


def _lpips_heads(state_dict: Mapping[str, Any], params: Dict[str, Any], n_heads: int) -> None:
    for stage in range(n_heads):
        # lpips package naming: lin{K}.model.1.weight; plain: lin{K}.weight
        for key in (f"lin{stage}.model.1.weight", f"lin{stage}.weight"):
            if key in state_dict:
                w = _to_numpy(state_dict[key])  # (1, C, 1, 1)
                params[f"lin{stage}"] = {"kernel": conv_to_flax(w)}
                break
        else:
            raise KeyError(f"missing LPIPS linear head lin{stage}")


def _lpips_backbone(
    state_dict: Mapping[str, Any], conv_indices: Tuple[int, ...], flax_names: Tuple[str, ...]
) -> Dict[str, Any]:
    """Shared LPIPS conversion: `features.N.{weight,bias}` convs + `linK` heads."""
    params: Dict[str, Any] = {
        name: _conv_entry(state_dict, f"features.{idx}")
        for idx, name in zip(conv_indices, flax_names)
    }
    _lpips_heads(state_dict, params, 5)
    return params


def convert_lpips_vgg16(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """torchvision VGG16 `features.*` + lpips `lin*` -> _LpipsBackbone('vgg') params."""
    return _lpips_backbone(state_dict, VGG16_CONV_INDICES, _VGG_FLAX_NAMES)


def convert_lpips_alexnet(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """torchvision AlexNet `features.*` + lpips `lin*` -> _LpipsBackbone('alex') params."""
    return _lpips_backbone(state_dict, ALEXNET_CONV_INDICES, _ALEX_FLAX_NAMES)


def convert_lpips_squeezenet(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """torchvision squeezenet1_1 `features.*` (conv1 + 8 Fire modules) +
    lpips `lin0..lin6` -> _LpipsBackbone('squeeze') params."""
    params: Dict[str, Any] = {"conv0": _conv_entry(state_dict, "features.0")}
    for idx in SQUEEZENET_FIRE_INDICES:
        params[f"fire{idx}"] = {
            sub: _conv_entry(state_dict, f"features.{idx}.{sub}")
            for sub in ("squeeze", "expand1x1", "expand3x3")
        }
    _lpips_heads(state_dict, params, 7)
    return params


def _natural_key(name: str) -> Tuple[str, int]:
    """'_ConvBN_10' -> ('_ConvBN_', 10): numeric-aware module ordering
    (flax param dicts sort alphabetically, which breaks past index 9)."""
    i = len(name)
    while i > 0 and name[i - 1].isdigit():
        i -= 1
    return name[:i], int(name[i:]) if i < len(name) else -1


def _walk_convbn_slots(tree: Mapping[str, Any], path: Tuple[str, ...] = ()) -> List[Tuple[str, ...]]:
    """Paths of every Conv+BatchNorm block in module-definition order."""
    slots: List[Tuple[str, ...]] = []
    if "Conv_0" in tree and "BatchNorm_0" in tree:
        slots.append(path)
        return slots
    for key in sorted(tree, key=_natural_key):
        value = tree[key]
        if isinstance(value, Mapping):
            slots.extend(_walk_convbn_slots(value, path + (key,)))
    return slots


def _set_path(tree: Dict[str, Any], path: Tuple[str, ...], value: Any) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def convert_inception_v3(
    state_dict: Mapping[str, Any], template_variables: Mapping[str, Any]
) -> Dict[str, Any]:
    """torch Inception-v3 (torchvision / torch-fidelity naming) -> Flax
    variables for ``FlaxInceptionV3``.

    Both torch models and the Flax module enumerate conv+bn blocks in the
    same definition order, so conversion walks the template's block slots and
    fills them from the state dict's ``(.conv.weight, .bn.*)`` groups in
    insertion order.  Every assignment is shape-checked; a topology mismatch
    raises instead of silently mis-assigning.

    Args:
        state_dict: torch state dict (ordered, as ``torch.load`` returns).
        template_variables: output of ``FlaxInceptionV3().init(...)`` (or the
            ``variables`` attribute of ``InceptionFeatureExtractor``).
    """
    # torchvision pretrained checkpoints carry an auxiliary classifier head
    # (AuxLogits.*) absent from the inference-only Flax trunk — skip it
    conv_keys = [
        k for k in state_dict
        if k.endswith(".conv.weight") and not k.startswith("AuxLogits.")
    ]
    params: Dict[str, Any] = {}
    batch_stats: Dict[str, Any] = {}
    slots = _walk_convbn_slots(template_variables["params"])
    if len(slots) != len(conv_keys):
        raise ValueError(
            f"Topology mismatch: template has {len(slots)} conv+bn blocks, "
            f"checkpoint has {len(conv_keys)}"
        )
    tmpl_params = template_variables["params"]
    for path, conv_key in zip(slots, conv_keys):
        prefix = conv_key[: -len(".conv.weight")]
        kernel = conv_to_flax(_to_numpy(state_dict[conv_key]))
        tmpl_node = tmpl_params
        for p in path:
            tmpl_node = tmpl_node[p]
        want = np.asarray(tmpl_node["Conv_0"]["kernel"]).shape
        if kernel.shape != want:
            raise ValueError(f"Shape mismatch at {'/'.join(path)}: {kernel.shape} vs {want}")
        _set_path(params, path + ("Conv_0",), {"kernel": kernel})
        _set_path(
            params, path + ("BatchNorm_0",),
            {"scale": _to_numpy(state_dict[f"{prefix}.bn.weight"]),
             "bias": _to_numpy(state_dict[f"{prefix}.bn.bias"])},
        )
        _set_path(
            batch_stats, path + ("BatchNorm_0",),
            {"mean": _to_numpy(state_dict[f"{prefix}.bn.running_mean"]),
             "var": _to_numpy(state_dict[f"{prefix}.bn.running_var"])},
        )
    if "fc.weight" in state_dict:
        params["Dense_0"] = {"kernel": linear_to_flax(_to_numpy(state_dict["fc.weight"]))}
    return {"params": params, "batch_stats": batch_stats}


def flatten_params(tree: Mapping[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    """Nested params pytree -> flat ``{'a/b/kernel': array}`` dict for npz."""
    out: Dict[str, np.ndarray] = {}
    for key, value in tree.items():
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(value, Mapping):
            out.update(flatten_params(value, path))
        else:
            out[path] = np.asarray(value)
    return out
