"""One-command fetch + convert of the published feature-extractor weights.

The reference's FID/IS/KID load the TF-graph-port Inception checkpoint
through torch-fidelity (``/root/reference/src/torchmetrics/image/fid.py:41-58``)
and LPIPS loads pretrained VGG16/AlexNet backbones + learned linear heads
through the lpips package (``image/lpip.py:23-43``).  This tool downloads the
same published checkpoint files directly (torch is only needed to deserialize
them — the torchvision/torch-fidelity/lpips *packages* are not required),
converts them into Flax pytrees with :mod:`tools.convert_weights`, and
installs them where :mod:`metrics_tpu.image.backbones.weights` discovers them.

Usage (on a machine with network access)::

    python -m tools.fetch_weights --all          # inception + lpips vgg/alex/squeeze
    python -m tools.fetch_weights --inception
    python -m tools.fetch_weights --lpips
    METRICS_TPU_WEIGHTS_DIR=/my/dir python -m tools.fetch_weights --all

Integrity: files whose canonical names embed a torch-hub hash prefix
(``-<8 hex chars>.pth``) are verified against sha256 before conversion; the
LPIPS head files (no embedded hash) are validated structurally (exact key
set and shapes).
"""

import argparse
import hashlib
import os
import sys
import tempfile
import urllib.request
from typing import Dict, Optional

import numpy as np

# TF-graph-port Inception used by FID/IS/KID (pytorch-fid release; the same
# 2015-12-05 TF dump torch-fidelity ships as weights-inception-2015-12-05-*)
INCEPTION_URL = (
    "https://github.com/mseitzer/pytorch-fid/releases/download/"
    "fid_weights/pt_inception-2015-12-05-6726825d.pth"
)
VGG16_URL = "https://download.pytorch.org/models/vgg16-397923af.pth"
ALEXNET_URL = "https://download.pytorch.org/models/alexnet-owt-7be5be79.pth"
SQUEEZENET_URL = "https://download.pytorch.org/models/squeezenet1_1-b8a52dc0.pth"
LPIPS_HEADS_URL = {
    "vgg": "https://raw.githubusercontent.com/richzhang/PerceptualSimilarity/master/lpips/weights/v0.1/vgg.pth",
    "alex": "https://raw.githubusercontent.com/richzhang/PerceptualSimilarity/master/lpips/weights/v0.1/alex.pth",
    "squeeze": "https://raw.githubusercontent.com/richzhang/PerceptualSimilarity/master/lpips/weights/v0.1/squeeze.pth",
}


def _hash_prefix_from_name(url: str) -> Optional[str]:
    """torch-hub convention: ``name-<hexdigits>.pth`` -> the hex prefix."""
    stem = os.path.basename(url).rsplit(".", 1)[0]
    tail = stem.rsplit("-", 1)[-1]
    if len(tail) >= 8 and all(c in "0123456789abcdef" for c in tail.lower()):
        return tail.lower()
    return None


def download(url: str, dest_dir: str) -> str:
    os.makedirs(dest_dir, exist_ok=True)
    dest = os.path.join(dest_dir, os.path.basename(url))
    if not os.path.isfile(dest):
        print(f"downloading {url}")
        with urllib.request.urlopen(url) as resp, open(dest + ".part", "wb") as f:
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
        os.replace(dest + ".part", dest)
    prefix = _hash_prefix_from_name(url)
    if prefix:
        digest = hashlib.sha256(open(dest, "rb").read()).hexdigest()
        if not digest.startswith(prefix):
            raise RuntimeError(f"sha256 mismatch for {dest}: {digest} !~ {prefix}")
        print(f"  sha256 ok ({prefix})")
    return dest


def _torch_load(path: str) -> Dict:
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=False)
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    return obj


def fetch_inception(out_dir: str, cache_dir: str, url: str = INCEPTION_URL) -> str:
    from metrics_tpu.image.backbones.inception import InceptionFeatureExtractor
    from metrics_tpu.image.backbones.weights import INCEPTION_FILE
    from tools.convert_weights import convert_inception_v3, flatten_params

    sd = _torch_load(download(url, cache_dir))
    template = InceptionFeatureExtractor("2048").variables
    variables = convert_inception_v3(sd, template)
    out = os.path.join(out_dir, INCEPTION_FILE)
    os.makedirs(out_dir, exist_ok=True)
    np.savez(out, **flatten_params(variables))
    print(f"wrote {out}")
    return out


def _validate_lpips_heads(sd: Dict, channels) -> None:
    for stage, ch in enumerate(channels):
        keys = [f"lin{stage}.model.1.weight", f"lin{stage}.weight"]
        found = next((k for k in keys if k in sd), None)
        if found is None:
            raise RuntimeError(f"LPIPS heads file is missing lin{stage}")
        shape = tuple(sd[found].shape)
        if shape != (1, ch, 1, 1):
            raise RuntimeError(f"LPIPS head lin{stage} has shape {shape}, expected (1, {ch}, 1, 1)")


def fetch_lpips(out_dir: str, cache_dir: str, net_type: str) -> str:
    from metrics_tpu.image.backbones.weights import LPIPS_FILES
    from tools.convert_weights import (
        convert_lpips_alexnet,
        convert_lpips_squeezenet,
        convert_lpips_vgg16,
        flatten_params,
    )

    backbone_url = {"vgg": VGG16_URL, "alex": ALEXNET_URL, "squeeze": SQUEEZENET_URL}[net_type]
    heads_channels = {
        "vgg": (64, 128, 256, 512, 512),
        "alex": (64, 192, 384, 256, 256),
        "squeeze": (64, 128, 256, 384, 384, 512, 512),
    }[net_type]
    backbone_sd = _torch_load(download(backbone_url, cache_dir))
    heads_sd = _torch_load(download(LPIPS_HEADS_URL[net_type], cache_dir))
    _validate_lpips_heads(heads_sd, heads_channels)
    merged = {**backbone_sd, **heads_sd}
    convert = {
        "vgg": convert_lpips_vgg16,
        "alex": convert_lpips_alexnet,
        "squeeze": convert_lpips_squeezenet,
    }[net_type]
    params = convert(merged)
    out = os.path.join(out_dir, LPIPS_FILES[net_type])
    os.makedirs(out_dir, exist_ok=True)
    np.savez(out, **flatten_params(params))
    print(f"wrote {out}")
    return out


def main(argv=None) -> int:
    from metrics_tpu.image.backbones.weights import default_weights_dir

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--all", action="store_true", help="fetch everything")
    parser.add_argument("--inception", action="store_true", help="FID/IS/KID Inception-v3")
    parser.add_argument("--lpips", action="store_true", help="LPIPS vgg + alex + squeeze")
    parser.add_argument("--out-dir", default=None, help="install dir (default: discovery path)")
    parser.add_argument("--cache-dir", default=None, help="raw .pth download cache")
    parser.add_argument("--inception-url", default=INCEPTION_URL)
    args = parser.parse_args(argv)
    if not (args.all or args.inception or args.lpips):
        parser.error("nothing to do: pass --all, --inception and/or --lpips")
    out_dir = args.out_dir or default_weights_dir()
    cache_dir = args.cache_dir or os.path.join(tempfile.gettempdir(), "metrics_tpu_downloads")
    if args.all or args.inception:
        fetch_inception(out_dir, cache_dir, url=args.inception_url)
    if args.all or args.lpips:
        fetch_lpips(out_dir, cache_dir, "vgg")
        fetch_lpips(out_dir, cache_dir, "alex")
        fetch_lpips(out_dir, cache_dir, "squeeze")
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
