"""Obs-instrumentation lint — thin shim over ``tools.analyze``.

The check lives in the ``obs-instrumentation`` pass
(``tools/analyze/passes/obs_instrumentation.py``); this module keeps the
legacy entry point and API alive.  Prefer ``python -m tools.analyze``.
"""

from __future__ import annotations

import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # imported by bare name with tools/ on sys.path
    sys.path.insert(0, _REPO)

from tools.analyze import run_passes
from tools.analyze.passes.obs_instrumentation import (  # noqa: F401  (legacy API)
    ALLOWLIST,
    INSTRUMENTED_METHODS,
)


def lint() -> List[str]:
    report = run_passes(["obs-instrumentation"], baseline_path=None)
    return [f.render() for f in report.findings]


def main() -> int:
    problems = lint()
    for p in problems:
        print(p)
    if problems:
        print(f"obs_lint: {len(problems)} problem(s)")
        return 1
    print("obs_lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
