"""Static check: every Metric subclass stays on the instrumented base-class path.

The obs spans/counters in ``metrics_tpu.obs`` are attached once, in
``Metric._update_wrapper`` / ``Metric._compute_wrapper`` / ``Metric.sync`` /
``Metric._finish_sync_report``.  A subclass that shadows one of those in its
class dict silently drops out of the telemetry (no update/compute spans, no
sync report recording) — which is exactly the kind of regression that never
shows up in functional tests.  This linter imports ``metrics_tpu``, walks the
full ``Metric`` subclass tree, and reports any first-party subclass that
overrides an instrumented method without being allowlisted.

Run directly (``python tools/obs_lint.py``) or via ``tests/test_obs_lint.py``.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Set, Tuple, Type

# allow running from a checkout without installing the package
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Methods that carry the instrumentation; overriding any of them in a class
# dict bypasses spans, recompile counters, or sync-report recording.
INSTRUMENTED_METHODS: Tuple[str, ...] = (
    "_update_wrapper",
    "_compute_wrapper",
    "_install_wrappers",
    "sync",
    "_finish_sync_report",
)

# (qualified class name) -> methods it may override.  CompositionalMetric
# re-dispatches through its operand metrics, each of which is spanned
# individually, so its wrapper overrides do not lose telemetry.
# MultiStreamMetric extends _finish_sync_report via super() to attribute
# stacked-state sync traffic to the multistream.sync_bytes counter — the
# base recording still runs first.
ALLOWLIST: Dict[str, Set[str]] = {
    "metrics_tpu.metric.CompositionalMetric": {"_update_wrapper", "_compute_wrapper"},
    "metrics_tpu.multistream.core.MultiStreamMetric": {"_finish_sync_report"},
}


def _walk_subclasses(cls: Type) -> List[Type]:
    out: List[Type] = []
    for sub in cls.__subclasses__():
        out.append(sub)
        out.extend(_walk_subclasses(sub))
    return out


def lint() -> List[str]:
    """Return a list of violation strings; empty means the tree is clean."""
    import metrics_tpu  # noqa: F401  (populates the subclass tree)
    from metrics_tpu.metric import Metric

    problems: List[str] = []
    seen: Set[Type] = set()
    for sub in _walk_subclasses(Metric):
        if sub in seen:
            continue
        seen.add(sub)
        if not sub.__module__.startswith("metrics_tpu"):
            continue  # user-defined subclasses are out of scope
        qualname = f"{sub.__module__}.{sub.__name__}"
        allowed = ALLOWLIST.get(qualname, set())
        for method in INSTRUMENTED_METHODS:
            if method in sub.__dict__ and method not in allowed:
                problems.append(
                    f"{qualname} overrides {method}(); it will bypass obs "
                    "instrumentation. Override update()/compute() instead, or "
                    "add an explicit allowlist entry in tools/obs_lint.py."
                )
    return problems


def main() -> int:
    problems = lint()
    for line in problems:
        print(f"obs_lint: {line}", file=sys.stderr)
    if problems:
        print(f"obs_lint: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print("obs_lint: all Metric subclasses on the instrumented path")
    return 0


if __name__ == "__main__":
    sys.exit(main())
