"""Static check: fixed-shape subsystems never use data-dependent shapes.

The streaming and multistream subsystems' whole contract is fixed-shape
state: a jitted ``update`` must never recompile as the stream grows, sketch
states must pack into fixed-size sync blobs, ring buffers must rotate in
place, and stacked ``(num_streams, ...)`` states must scatter without
reshaping.  One stray ``jnp.nonzero`` / ``.item()`` / boolean-mask
extraction silently breaks that — it traces fine in eager tests and then
either crashes under jit or, worse, forces a retrace per batch.

This linter AST-walks every module under ``metrics_tpu/streaming/`` and
``metrics_tpu/multistream/`` and flags:

* calls producing data-dependent output shapes: ``nonzero``,
  ``flatnonzero``, ``argwhere``, ``unique``, ``extract``, ``compress``,
  ``repeat`` with array counts is out of scope (numpy-host only), and
  single-argument ``where`` (the three-argument form is shape-static);
* host round-trips inside state math: ``.item()`` / ``.tolist()`` on
  computed values;
* growing state kinds: any ``add_buffer_state`` call, or ``add_state`` with
  a ``[]`` (list-state) default.

Run directly (``python tools/shape_lint.py``) or via
``tests/test_shape_lint.py``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

STREAMING_DIR = os.path.join(_REPO_ROOT, "metrics_tpu", "streaming")

# every directory whose modules must keep state math shape-static
LINTED_DIRS = (
    STREAMING_DIR,
    os.path.join(_REPO_ROOT, "metrics_tpu", "multistream"),
    # the serving path dispatches compiled blocks: the same static-shape
    # discipline applies to everything between the queue and the metric
    os.path.join(_REPO_ROOT, "metrics_tpu", "serve"),
)

# call names whose result shape depends on data values
DYNAMIC_SHAPE_CALLS = {
    "nonzero",
    "flatnonzero",
    "argwhere",
    "unique",
    "unique_values",
    "extract",
    "compress",
    "setdiff1d",
    "union1d",
    "intersect1d",
}

# host-pull methods that would put a device sync inside state math
HOST_PULL_CALLS = {"item", "tolist"}


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def lint_source(src: str, filename: str) -> List[str]:
    """Lint one module's source; returns violation strings."""
    problems: List[str] = []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as err:
        return [f"{filename}:{err.lineno}: does not parse: {err.msg}"]
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        where = f"{filename}:{node.lineno}"
        if name in DYNAMIC_SHAPE_CALLS:
            problems.append(
                f"{where}: `{name}` produces a data-dependent shape; streaming "
                "state must stay fixed-shape (mask with 3-arg `where` instead)"
            )
        elif name == "where" and len(node.args) == 1 and not node.keywords:
            problems.append(
                f"{where}: single-argument `where` is data-dependent "
                "(returns indices); use the 3-argument select form"
            )
        elif name in HOST_PULL_CALLS and isinstance(node.func, ast.Attribute):
            problems.append(
                f"{where}: `.{name}()` forces a host round-trip inside "
                "streaming code; keep state math on device"
            )
        elif name == "add_buffer_state":
            problems.append(
                f"{where}: buffer states grow with the stream; streaming "
                "metrics must use fixed-shape tensor or sketch states"
            )
        elif name == "add_state" and any(
            isinstance(a, ast.List) and not a.elts for a in node.args
        ):
            problems.append(
                f"{where}: list-state default `[]` grows with the stream; "
                "streaming metrics must use fixed-shape tensor or sketch states"
            )
    return problems


def lint() -> List[str]:
    """Lint every module under the shape-static subsystem directories."""
    problems: List[str] = []
    for lint_dir in LINTED_DIRS:
        for base, _dirs, files in sorted(os.walk(lint_dir)):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(base, fname)
                with open(path, "r", encoding="utf-8") as fh:
                    src = fh.read()
                rel = os.path.relpath(path, _REPO_ROOT)
                problems.extend(lint_source(src, rel))
    return problems


def main() -> int:
    problems = lint()
    for line in problems:
        print(f"shape_lint: {line}", file=sys.stderr)
    if problems:
        print(f"shape_lint: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print("shape_lint: streaming/, multistream/ and serve/ state is shape-static")
    return 0


if __name__ == "__main__":
    sys.exit(main())
