"""Static shape-discipline lint — thin shim over ``tools.analyze``.

The checks live in the ``shape-static`` pass
(``tools/analyze/passes/shape_static.py``); this module keeps the legacy
entry point (``python tools/shape_lint.py``) and API (``lint_source`` /
``lint`` / ``LINTED_DIRS``) alive.  Prefer ``python -m tools.analyze``.
"""

from __future__ import annotations

import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # imported by bare name with tools/ on sys.path
    sys.path.insert(0, _REPO)

from tools.analyze import analyze_source, run_passes
from tools.analyze.passes.shape_static import (  # noqa: F401  (legacy API)
    DYNAMIC_SHAPE_CALLS,
    HOST_PULL_CALLS,
    SCOPE_PREFIXES,
)

# legacy alias: scope now lives on the pass; discovery replaced the dir list
LINTED_DIRS = tuple(p.split("/", 1)[1].rstrip("/") for p in SCOPE_PREFIXES)


def lint_source(src: str, filename: str) -> List[str]:
    """Lint one source string unconditionally (legacy behavior)."""
    rel = filename.replace(os.sep, "/")
    if not rel.startswith(SCOPE_PREFIXES):
        rel = SCOPE_PREFIXES[0] + os.path.basename(rel)
    return [f.render() for f in analyze_source("shape-static", src, rel=rel)]


def lint() -> List[str]:
    report = run_passes(["shape-static"], baseline_path=None)
    return [f.render() for f in report.findings]


def main() -> int:
    problems = lint()
    for p in problems:
        print(p)
    if problems:
        print(f"shape_lint: {len(problems)} problem(s)")
        return 1
    print("shape_lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
